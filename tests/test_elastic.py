"""Elastic training: checkpoint/recovery + live resharding
(parallel/elastic.py, ISSUE 7).

The reference has only ps-lite heartbeat dead-node detection
(ref: src/kvstore/kvstore_dist.h:121 GetDeadNodes) and no checkpoint
recovery (SURVEY §5); these tests pin the TPU-native upgrade: resume
after simulated collective failures, preemption-save semantics with
handler chaining, incomplete-checkpoint hygiene, bitwise-deterministic
resume, controller-driven resharding, and the slow 2-process rank-kill
chaos run (SIGKILL mid-epoch -> reshard -> converge, bitwise-equal to a
clean run resumed from the same checkpoint)."""
import json
import os
import shutil
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import jax.random as jr

from mxnet_tpu import profiler
from mxnet_tpu.parallel import (CheckpointManager, ElasticController,
                                HostGradReducer, PreemptionGuard,
                                ReshardRequired, create_mesh,
                                data_parallel, elastic_train_loop,
                                relayout_params, shard_for_rank,
                                shrink_mesh, surviving_devices)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mgr(tmp_path, **kw):
    return CheckpointManager(str(tmp_path / "ckpt"), **kw)


@pytest.mark.parametrize("use_orbax", [False, True])
def test_checkpoint_roundtrip(tmp_path, use_orbax):
    if use_orbax:
        pytest.importorskip("orbax.checkpoint")
    m = CheckpointManager(str(tmp_path / ("o" if use_orbax else "p")),
                          use_orbax=use_orbax)
    state = {"w": jnp.arange(4.0), "step": jnp.asarray(7)}
    m.save(10, state)
    m.save(20, state)
    assert m.latest_step() == 20
    restored, step = m.restore()
    assert step == 20
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(restored)[0]).ravel()[:4]
        if not isinstance(restored, dict) else np.asarray(restored["w"]),
        np.arange(4.0))


def test_checkpoint_prune(tmp_path):
    m = _mgr(tmp_path, keep=2, use_orbax=False)
    for s in (1, 2, 3, 4):
        m.save(s, {"x": jnp.zeros(1)})
    assert m.all_steps() == [3, 4]


def test_elastic_loop_recovers_from_failures(tmp_path):
    """A step that fails twice mid-run: the loop must restore and finish
    with EXACTLY the same result as an uninterrupted run."""
    m = _mgr(tmp_path, use_orbax=False)
    batches = [jnp.asarray(float(i)) for i in range(10)]

    fail_at = {5: 2}  # step 5 fails twice

    def make_step(fail_budget):
        def step(state, b):
            if fail_budget.get(int(b), 0) > 0:
                fail_budget[int(b)] -= 1
                raise RuntimeError("simulated collective failure")
            return {"acc": state["acc"] + b}, None
        return step

    state0 = {"acc": jnp.asarray(0.0)}
    state, last, done = elastic_train_loop(
        make_step(dict(fail_at)), dict(state0), batches, m, save_every=2,
        max_failures=5)
    assert done and last == 9
    np.testing.assert_allclose(float(state["acc"]), sum(range(10)))


def test_elastic_loop_gives_up_after_max_failures(tmp_path):
    m = _mgr(tmp_path, use_orbax=False)

    def step(state, b):
        raise RuntimeError("permanently broken")

    with pytest.raises(RuntimeError, match="permanently broken"):
        elastic_train_loop(step, {"acc": jnp.asarray(0.0)},
                           [jnp.asarray(1.0)] * 3, m, save_every=1,
                           max_failures=2)


def test_elastic_resume_from_existing_checkpoint(tmp_path):
    """A fresh loop (new process after preemption) picks up from the
    newest checkpoint instead of step 0."""
    m = _mgr(tmp_path, use_orbax=False)
    seen = []

    def step(state, b):
        seen.append(float(b))
        return {"acc": state["acc"] + b}, None

    batches = [jnp.asarray(float(i)) for i in range(6)]
    # simulate an earlier incarnation that saved at step 3
    m.save(3, {"acc": jnp.asarray(float(0 + 1 + 2 + 3))})
    state, last, done = elastic_train_loop(
        step, {"acc": jnp.asarray(0.0)}, batches, m, save_every=100)
    assert done
    assert seen == [4.0, 5.0]          # steps 0..3 skipped
    np.testing.assert_allclose(float(state["acc"]), 15.0)


def test_preemption_guard_saves_and_exits(tmp_path):
    m = _mgr(tmp_path, use_orbax=False)

    def step(state, b):
        if float(b) == 2.0:
            # deliver the preemption signal mid-run
            os.kill(os.getpid(), signal.SIGTERM)
        return {"acc": state["acc"] + b}, None

    batches = [jnp.asarray(float(i)) for i in range(10)]
    state, last, done = elastic_train_loop(
        step, {"acc": jnp.asarray(0.0)}, batches, m, save_every=100)
    assert not done
    # checkpoint exists so the next incarnation resumes
    restored, step_no = m.restore()
    assert restored is not None and step_no == last
    state2, last2, done2 = elastic_train_loop(
        step, {"acc": jnp.asarray(0.0)}, batches, m, save_every=100)
    assert done2
    np.testing.assert_allclose(float(state2["acc"]), sum(range(10)))


# -- incomplete-checkpoint hygiene (ISSUE 7 satellite 1) ----------------------

class TestIncompleteCheckpoints:
    def test_truncated_newest_restores_previous(self, tmp_path):
        """A crash between multi-host shard writes leaves a truncated
        file: it must never be a restore candidate, and the previous
        complete step must restore."""
        m = _mgr(tmp_path, use_orbax=False, keep=10)
        m.save(1, {"x": jnp.arange(3.0)})
        m.save(2, {"x": jnp.arange(3.0) * 2})
        p2 = m._step_path(2)
        data = open(p2, "rb").read()
        with open(p2, "wb") as f:
            f.write(data[: len(data) // 2])
        assert m.latest_step() == 1
        state, step = m.restore()
        assert step == 1
        np.testing.assert_allclose(
            np.asarray(jax.tree_util.tree_leaves(state)[0]),
            np.arange(3.0))

    def test_unreadable_but_complete_looking_is_skipped(self, tmp_path):
        """Corruption past the STOP byte: the cheap probe passes but
        unpickling fails — restore() walks to the previous step and
        counts the skip."""
        m = _mgr(tmp_path, use_orbax=False, keep=10)
        m.save(1, {"x": jnp.arange(3.0)})
        with open(m._step_path(5), "wb") as f:
            f.write(b"\x93garbage-not-a-pickle.")
        assert m.latest_step() == 5  # probe can't tell
        before = profiler.elastic_stats().get("incomplete_skipped", 0)
        state, step = m.restore()
        assert step == 1
        assert profiler.elastic_stats().get(
            "incomplete_skipped", 0) == before + 1

    def test_incomplete_pruned_on_next_save(self, tmp_path):
        m = _mgr(tmp_path, use_orbax=False, keep=10)
        m.save(1, {"x": jnp.zeros(2)})
        # a truncated step + a stale .tmp from a SIGKILLed save
        with open(m._step_path(2), "wb") as f:
            f.write(b"\x80\x04trunc")
        with open(m._step_path(3) + ".tmp", "wb") as f:
            f.write(b"partial")
        m.save(4, {"x": jnp.zeros(2)})
        names = sorted(os.listdir(m.directory))
        assert names == ["step_1.ckpt", "step_4.ckpt"]

    def test_orbax_dir_without_commit_marker_skipped(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        m = CheckpointManager(str(tmp_path / "o"), use_orbax=True,
                              keep=10)
        m.save(1, {"x": jnp.arange(4.0)})
        m.save(2, {"x": jnp.arange(4.0) * 3})
        # simulate a crash between multi-host shard writes: step dir
        # exists but the commit marker never landed
        os.remove(os.path.join(m._step_path(2), "_COMMIT"))
        assert m.latest_step() == 1
        state, step = m.restore()
        assert step == 1
        m.save(3, {"x": jnp.arange(4.0)})
        assert m.all_steps() == [1, 3]
        assert not os.path.exists(m._step_path(2))


# -- PreemptionGuard chaining (ISSUE 7 satellite 2) ---------------------------

_GUARD_SCRIPT = r"""
import os, signal, sys
sys.path.insert(0, %(repo)r)
from mxnet_tpu.parallel.elastic import PreemptionGuard

calls = []
def old_handler(signum, frame):
    calls.append(signum)
signal.signal(signal.SIGTERM, old_handler)

with PreemptionGuard() as g:
    os.kill(os.getpid(), signal.SIGTERM)
    assert g.preempted
    assert calls == [signal.SIGTERM], calls       # chained, once
    os.kill(os.getpid(), signal.SIGTERM)
    assert calls == [signal.SIGTERM], calls       # at most once
    assert g.preempted
assert signal.getsignal(signal.SIGTERM) is old_handler  # restored
os.kill(os.getpid(), signal.SIGTERM)
assert calls == [signal.SIGTERM, signal.SIGTERM], calls
print("GUARD_OK")
"""


def test_preemption_guard_chains_and_restores():
    """SIGTERM delivered for real (subprocess): the guard chains to the
    pre-existing handler exactly once, ignores repeats, and restores
    the handler on __exit__."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-c", _GUARD_SCRIPT % {"repo": REPO}],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "GUARD_OK" in r.stdout


# -- bitwise-deterministic resume (ISSUE 7 satellite 4) -----------------------

def _noisy_step(state, idx):
    """Deterministic quadratic step with rng-derived noise: resume is
    bitwise only if params, momentum AND the rng key round-trip."""
    rs = np.random.RandomState(100 + int(idx))
    X = jnp.asarray(rs.randn(4, 8).astype(np.float32))
    g = X.T @ (X @ state["w"])
    key, sub = jr.split(state["rng"])
    g = g + 0.01 * jr.normal(sub, g.shape, jnp.float32)
    m = 0.9 * state["m"] + g
    return {"w": state["w"] - 0.05 * m, "m": m, "rng": key}, None


def test_bitwise_deterministic_resume(tmp_path):
    """Kill at step k (SIGTERM mid-run), restart, reach step N: params
    bitwise-equal to an uninterrupted run (rng + optimizer state
    round-trip through the checkpoint)."""
    state0 = {"w": jnp.ones((8,), jnp.float32),
              "m": jnp.zeros((8,), jnp.float32),
              "rng": jr.PRNGKey(3)}
    batches = list(range(12))

    m_a = CheckpointManager(str(tmp_path / "a"), use_orbax=False)
    s_a, last_a, done_a = elastic_train_loop(
        _noisy_step, dict(state0), batches, m_a, save_every=3)
    assert done_a and last_a == 11

    m_b = CheckpointManager(str(tmp_path / "b"), use_orbax=False,
                            keep=10)

    def killing_step(state, idx):
        out = _noisy_step(state, idx)
        if int(idx) == 7:
            os.kill(os.getpid(), signal.SIGTERM)
        return out

    s_b1, last_b1, done_b1 = elastic_train_loop(
        killing_step, dict(state0), batches, m_b, save_every=3)
    assert not done_b1 and last_b1 == 7
    # second incarnation resumes from the preemption checkpoint
    s_b, last_b, done_b = elastic_train_loop(
        _noisy_step, dict(state0), batches, m_b, save_every=3)
    assert done_b and last_b == 11
    for k in ("w", "m", "rng"):
        assert np.array_equal(np.asarray(s_a[k]), np.asarray(s_b[k])), k


# -- controller / resharding --------------------------------------------------

class _FakeKV:
    def __init__(self, nworkers=3):
        self.dead = []
        self.num_workers = nworkers
        self.resized = []

    def dead_nodes(self, timeout=3.0):
        return list(self.dead)

    def resize(self, n):
        self.resized.append(int(n))
        self.num_workers = int(n)


class TestElasticController:
    def test_poll_reshard_cycle(self):
        kv = _FakeKV(3)
        seen = []
        ctl = ElasticController(kvstore=kv, world=range(3), rank=0,
                                poll_interval=0.0,
                                reshard_fn=lambda st, w: seen.append(
                                    (st, list(w))))
        assert ctl.poll() == []
        kv.dead = [2]
        assert ctl.poll(force=True) == [2]
        assert ctl.poll(force=True) == []          # reported once
        assert ctl.survivors == [0, 1]
        assert ctl.handle_failure(RuntimeError("boom"))
        before = profiler.elastic_stats().get("reshards", 0)
        survivors, _ = ctl.reshard({"s": 1})
        assert survivors == [0, 1]
        assert kv.resized == [2]
        assert seen == [({"s": 1}, [0, 1])]
        assert profiler.elastic_stats().get("reshards", 0) == before + 1
        # dead rank no longer in the committed world: a later transient
        # failure is retried, not resharded
        assert not ctl.handle_failure(RuntimeError("transient"))

    def test_poll_ignores_out_of_world_deaths(self):
        # a controller scoped to a sub-world of a shared PS (or one
        # whose world already shrank) must not reshard-and-rewind when
        # a rank OUTSIDE its committed world dies
        kv = _FakeKV(4)
        ctl = ElasticController(kvstore=kv, world=[0, 1], rank=0,
                                poll_interval=0.0)
        kv.dead = [3]
        assert ctl.poll(force=True) == []          # noted, not actionable
        assert 3 in ctl._dead                      # still tracked
        assert not ctl.handle_failure(RuntimeError("transient"))
        kv.dead = [1, 3]                           # now an in-world death
        assert ctl.poll(force=True) == [1]

    def test_fail_policy_raises(self):
        kv = _FakeKV(2)
        kv.dead = [1]
        ctl = ElasticController(kvstore=kv, world=range(2), rank=0,
                                poll_interval=0.0,
                                reshard_policy="fail")
        ctl.poll(force=True)
        with pytest.raises(ReshardRequired):
            ctl.reshard()

    def test_loop_reshards_on_dead_rank(self, tmp_path):
        """A step failure attributed to a dead rank reshards (instead of
        exhausting max_failures) and the loop completes on the
        survivors."""
        kv = _FakeKV(2)
        ctl = ElasticController(kvstore=kv, world=range(2), rank=0,
                                poll_interval=0.0)
        m = _mgr(tmp_path, use_orbax=False)
        live_world = []

        def step(state, b):
            world = ctl.survivors
            if int(b) == 3 and len(world) == 2:
                kv.dead = [1]          # rank 1 vanishes mid-epoch
                raise ConnectionError("collective failed: peer gone")
            live_world.append((int(b), list(world)))
            return {"acc": state["acc"] + b}, None

        state, last, done = elastic_train_loop(
            step, {"acc": jnp.asarray(0.0)},
            [jnp.asarray(float(i)) for i in range(6)], m,
            save_every=1, max_failures=0, controller=ctl)
        assert done and last == 5
        assert kv.resized == [1]
        # steps 3.. ran on the shrunk world
        assert ([w for b, w in live_world if b == 3][-1]) == [0]
        np.testing.assert_allclose(float(state["acc"]), sum(range(6)))

    def test_shard_for_rank_is_deterministic_partition(self):
        for n in (1, 2, 3, 5, 8):
            world = list(range(n))
            all_rows = []
            for r in world:
                all_rows.extend(shard_for_rank(10, world, r))
            assert sorted(all_rows) == list(range(10))
        # pure function of (n_items, world, rank): survivors agree
        assert list(shard_for_rank(8, [0, 2], 2)) == [4, 5, 6, 7]
        assert list(shard_for_rank(8, [0], 0)) == list(range(8))


class TestMeshResharding:
    def test_shrink_mesh_drops_dead_process_devices(self):
        mesh = create_mesh(dp=8)
        # single-host CPU: every device is process 0; simulate a lost
        # host by dropping half the devices explicitly
        keep = list(jax.devices()[:4])
        small = shrink_mesh(mesh, devices=keep)
        assert small.size() == 4
        assert dict(small.shape)["dp"] == 4
        assert surviving_devices([], keep) == keep

    def test_shrink_preserves_model_axes(self):
        mesh = create_mesh(dp=4, tp=2)
        small = shrink_mesh(mesh, devices=list(jax.devices()[:4]))
        assert dict(small.shape)["tp"] == 2
        assert dict(small.shape)["dp"] == 2
        with pytest.raises(ValueError):
            shrink_mesh(mesh, devices=[])

    def test_relayout_params_onto_new_mesh(self):
        mesh = create_mesh(dp=8)
        strategy = data_parallel(mesh)
        params = {"w": jnp.arange(16.0).reshape(4, 4)}
        placed = relayout_params(params, strategy)
        assert len(placed["w"].sharding.device_set) == 8
        small = shrink_mesh(mesh, devices=list(jax.devices()[:2]))
        placed2 = relayout_params(placed, data_parallel(small))
        assert len(placed2["w"].sharding.device_set) == 2
        np.testing.assert_array_equal(np.asarray(placed2["w"]),
                                      np.asarray(params["w"]))


class TestDeadNodeSignal:
    def test_kvstore_dead_nodes_counts_growth_once(self, monkeypatch):
        """AsyncKVStore.dead_nodes wraps the _OP_DEADNODES wire op and
        counts each newly-dead rank exactly once into
        metrics()['elastic']['dead_rank_detected']."""
        import mxnet_tpu as mx
        monkeypatch.delenv("MXTPU_COORDINATOR", raising=False)
        monkeypatch.setenv("MXTPU_PROC_ID", "0")
        monkeypatch.setenv("MXTPU_NUM_PROCS", "1")
        monkeypatch.setenv("MXTPU_ASYNC_PS_PORT", "0")
        # keep our own rank-0 heartbeat fresher than the poll timeout
        # so only the ghost rank goes stale
        monkeypatch.setenv("MXTPU_PS_HEARTBEAT_INTERVAL", "0.05")
        kv = mx.kv.create("dist_async")
        try:
            from mxnet_tpu.kvstore_async import AsyncPSClient
            ghost = AsyncPSClient("127.0.0.1", kv._server.port)
            ghost.heartbeat(9)     # one beat, then silence
            time.sleep(0.3)
            before = profiler.elastic_stats().get(
                "dead_rank_detected", 0)
            dead = kv.dead_nodes(timeout=0.2)
            assert 9 in dead
            after = profiler.elastic_stats().get("dead_rank_detected", 0)
            assert after == before + 1
            kv.dead_nodes(timeout=0.2)   # same set: no re-count
            assert profiler.elastic_stats().get(
                "dead_rank_detected", 0) == after
        finally:
            kv.close()

    def test_resize_validates(self, monkeypatch):
        import mxnet_tpu as mx
        monkeypatch.delenv("MXTPU_COORDINATOR", raising=False)
        monkeypatch.setenv("MXTPU_PROC_ID", "0")
        monkeypatch.setenv("MXTPU_NUM_PROCS", "1")
        monkeypatch.setenv("MXTPU_ASYNC_PS_PORT", "0")
        kv = mx.kv.create("dist_async")
        try:
            with pytest.raises(ValueError):
                kv.resize(0)
            kv.resize(1)
            assert kv.num_workers == 1
        finally:
            kv.close()


# -- slow: the ISSUE 7 acceptance chaos run -----------------------------------

@pytest.mark.slow
def test_rank_kill_chaos_reshards_and_resumes_bitwise(tmp_path):
    """2 processes, rank 1 SIGKILLed mid-epoch: the job reshards onto
    the survivor, resumes from the newest crash-consistent checkpoint,
    converges (no hang — bounded by the barrier/retry deadlines), the
    recovery is fully accounted in metrics()['elastic'], and the final
    params are BITWISE equal to a clean run resumed from the same
    checkpoint."""
    ckpt = tmp_path / "ckpt"
    out = tmp_path / "out"
    ckpt.mkdir()
    out.mkdir()
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
        "MXTPU_CHAOS_CKPT_DIR": str(ckpt),
        "MXTPU_CHAOS_OUT_DIR": str(out),
        "MXTPU_CHAOS_DIE_RANK": "1", "MXTPU_CHAOS_DIE_AT": "13",
        "MXTPU_CHAOS_STEPS": "30", "MXTPU_CHAOS_SAVE_EVERY": "5",
        "MXTPU_PS_HEARTBEAT_INTERVAL": "0.1",
        "MXTPU_PS_BARRIER_TIMEOUT": "4",
        "MXTPU_PS_DEAD_TIMEOUT": "1.0",
        "MXTPU_ELASTIC_POLL_S": "0.2",
        "MXTPU_PS_DONE_TIMEOUT": "10",
    })
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "--elastic", "-n", "2", sys.executable,
         os.path.join(REPO, "tests", "elastic_chaos_worker.py")],
        env=env, capture_output=True, text=True, timeout=420)
    log = r.stdout + r.stderr
    assert r.returncode == 0, log              # no hang, survivor clean
    assert "ELASTIC_OK rank=0 done=True" in log, log

    # full fault accounting in metrics()['elastic']
    metrics_line = [ln for ln in log.splitlines()
                    if ln.startswith("ELASTIC_METRICS rank=0")][0]
    elastic = json.loads(metrics_line.split(None, 2)[2])
    assert elastic.get("dead_rank_detected", 0) >= 1, elastic
    assert elastic.get("reshards", 0) == 1, elastic
    assert elastic.get("restores", 0) >= 1, elastic
    assert elastic.get("checkpoint_saves", 0) >= 2, elastic

    # the survivor restored from this step after the SIGKILL
    restored = [int(ln.split("step=")[1].split()[0])
                for ln in log.splitlines()
                if ln.startswith("ELASTIC_RESTORED rank=0")]
    assert restored, log
    resumed_from = restored[0]
    assert resumed_from < 13

    w_chaos = np.load(out / "params_rank0.npy")
    # convergence: error vs the generating weights shrank from 1.0
    from tests.elastic_chaos_worker import W_TRUE
    assert float(np.max(np.abs(w_chaos - W_TRUE))) < 0.6

    # clean reference: a fresh single-process incarnation resumed from
    # the SAME checkpoint must reach bitwise-identical params
    ckpt2 = tmp_path / "ckpt_clean"
    ckpt2.mkdir()
    shutil.copy(ckpt / ("step_%d.ckpt" % resumed_from),
                ckpt2 / ("step_%d.ckpt" % resumed_from))
    out2 = tmp_path / "out_clean"
    out2.mkdir()
    env2 = dict(env)
    env2.update({
        "MXTPU_NUM_PROCS": "1", "MXTPU_PROC_ID": "0",
        "MXTPU_CHAOS_CKPT_DIR": str(ckpt2),
        "MXTPU_CHAOS_OUT_DIR": str(out2),
        "MXTPU_CHAOS_DIE_RANK": "-1", "MXTPU_CHAOS_DIE_AT": "-1",
    })
    r2 = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tests", "elastic_chaos_worker.py")],
        env=env2, capture_output=True, text=True, timeout=300)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert ("ELASTIC_RESTORED rank=0 step=%d" % resumed_from) \
        in r2.stdout
    w_clean = np.load(out2 / "params_rank0.npy")
    assert np.array_equal(w_chaos, w_clean), \
        np.max(np.abs(w_chaos - w_clean))
