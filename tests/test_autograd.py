"""Autograd tests (model: tests/python/unittest/test_autograd.py,
test_higher_order_grad.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal


def test_basic_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x + 2 * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy() + 2)


def test_chain_and_branches():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        a = x * 2
        b = a + x        # x used twice
        y = (b * b).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * (3 * x.asnumpy()) * 3)


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10.0, 100.0]))
    assert_almost_equal(x.grad, np.array([30.0, 300.0], np.float32))


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad, 3 * 2 * x.asnumpy())


def test_pause_and_detach():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        with autograd.pause():
            z = y * 3          # not recorded
        w = y + z.detach()
    w.backward()
    assert_almost_equal(x.grad, np.array([4.0], np.float32))


def test_training_modes():
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_training() and autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training() and not autograd.is_recording()


def test_multi_head_backward():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y1 = (x * x).sum()
        y2 = (x * 3).sum()
    autograd.backward([y1, y2])
    assert_almost_equal(x.grad, 2 * x.asnumpy() + 3)


def test_autograd_grad_api():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x ** 2).sum()
        g = autograd.grad(y, x)
    assert_almost_equal(g, 2 * x.asnumpy())


def test_higher_order():
    x = nd.array([0.5, 1.0, 1.5])
    x.attach_grad()
    with autograd.record():
        y = (x ** 4).sum()
        gx = autograd.grad(y, x, create_graph=True)
        z = gx.sum()
    z.backward()
    # d2y/dx2 = 12 x^2
    assert_almost_equal(x.grad, 12 * x.asnumpy() ** 2, rtol=1e-4)


def test_third_order():
    x = nd.array([0.7])
    x.attach_grad()
    with autograd.record():
        y = x ** 4
        g1 = autograd.grad(y, x, create_graph=True)
        g2 = autograd.grad(g1, x, create_graph=True)
        z = g2.sum()
    z.backward()
    assert_almost_equal(x.grad, 24 * x.asnumpy(), rtol=1e-4)


def test_stop_gradient():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * nd.stop_gradient(x)  # d/dx = x (second factor constant)
    y.backward()
    assert_almost_equal(x.grad, x.asnumpy())


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-5)


def test_inplace_raises_when_recorded():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with pytest.raises(RuntimeError):
            y += 1


def test_exception_propagation():
    # errors inside ops surface at call site (engine exception analog,
    # ref: tests/python/unittest/test_exc_handling.py)
    a = nd.ones((2, 3))
    b = nd.ones((4, 5))
    with pytest.raises(Exception):
        nd.dot(a, b).wait_to_read()
