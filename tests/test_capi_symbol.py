"""Frontier C ABI tests: Symbol / Executor / KVStore / DataIter /
NDArray save-load surfaces (src/c_api_symbol.cc).

The end-to-end test is the VERDICT done-criterion: a pure-C program
(example/capi/train_symbol.c) binds a Symbol loaded from JSON, trains
it through a KVStore-held optimizer fed by a DataIter, and writes a
checkpoint that Python loads back.

ref: include/mxnet/c_api.h — MXSymbolCreateFromJSON family,
MXExecutorSimpleBindEx, MXKVStore*, MXDataIter*, MXNDArraySave/Load
:638-672.
"""
import ctypes
import os
import shutil
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "mxnet_tpu", "libmxnet_tpu.so")
DEMO = os.path.join(REPO, "example", "capi", "train_symbol.c")


def _build_lib():
    if not os.path.exists(LIB):
        subprocess.run(["make", "-C", os.path.join(REPO, "src")],
                       check=True, capture_output=True)
    lib = ctypes.CDLL(LIB)
    return lib if hasattr(lib, "MXTSymbolCreateFromJSON") else None


def _mlp_symbol():
    data = mx.sym.var("data")
    label = mx.sym.var("label")
    fc = mx.sym.FullyConnected(data, num_hidden=1, name="fc1")
    return mx.sym.LinearRegressionOutput(fc, label, name="lro")


@pytest.fixture(scope="module")
def lib():
    lib = _build_lib()
    if lib is None:
        pytest.skip("frontier C ABI not built")
    lib.MXTGetLastError.restype = ctypes.c_char_p
    vp, u32 = ctypes.c_void_p, ctypes.c_uint32
    vpp = ctypes.POINTER(vp)
    ccp = ctypes.POINTER(ctypes.c_char_p)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u32p = ctypes.POINTER(u32)
    lib.MXTSymbolCreateFromJSON.argtypes = [ctypes.c_char_p, vpp]
    lib.MXTSymbolSaveToJSON.argtypes = [vp, ccp]
    lib.MXTSymbolCreateVariable.argtypes = [ctypes.c_char_p, vpp]
    lib.MXTSymbolCreateAtomicSymbol.argtypes = [ctypes.c_char_p, u32,
                                                ccp, ccp, vpp]
    lib.MXTSymbolCompose.argtypes = [vp, ctypes.c_char_p, u32, ccp, vpp,
                                     vpp]
    lib.MXTSymbolListArguments.argtypes = [vp, u32p,
                                           ctypes.POINTER(ccp)]
    lib.MXTSymbolListOutputs.argtypes = [vp, u32p, ctypes.POINTER(ccp)]
    lib.MXTSymbolInferShape.argtypes = [vp, u32, ccp, u32p, i64p, u32p,
                                        u32p, u32p,
                                        ctypes.POINTER(u32p),
                                        ctypes.POINTER(i64p)]
    lib.MXTSymbolFree.argtypes = [vp]
    lib.MXTExecutorSimpleBind.argtypes = [vp, u32, ccp, u32p, i64p,
                                          ctypes.c_char_p, vpp]
    lib.MXTExecutorForward.argtypes = [vp, ctypes.c_int]
    lib.MXTExecutorBackward.argtypes = [vp, u32, vpp]
    lib.MXTExecutorOutputs.argtypes = [vp, u32p, vpp, u32]
    lib.MXTExecutorArgArray.argtypes = [vp, ctypes.c_char_p, vpp]
    lib.MXTExecutorGradArray.argtypes = [vp, ctypes.c_char_p, vpp]
    lib.MXTExecutorFree.argtypes = [vp]
    lib.MXTKVStoreCreate.argtypes = [ctypes.c_char_p, vpp]
    lib.MXTKVStoreInit.argtypes = [vp, ctypes.c_int, vp]
    lib.MXTKVStorePush.argtypes = [vp, ctypes.c_int, vp, ctypes.c_int]
    lib.MXTKVStorePull.argtypes = [vp, ctypes.c_int, vp, ctypes.c_int]
    lib.MXTKVStoreGetRank.argtypes = [vp, ctypes.POINTER(ctypes.c_int)]
    lib.MXTKVStoreGetType.argtypes = [vp, ccp]
    lib.MXTKVStoreFree.argtypes = [vp]
    lib.MXTDataIterCreate.argtypes = [ctypes.c_char_p, u32, ccp, ccp, vpp]
    lib.MXTDataIterNext.argtypes = [vp, ctypes.POINTER(ctypes.c_int)]
    lib.MXTDataIterGetData.argtypes = [vp, vpp]
    lib.MXTDataIterFree.argtypes = [vp]
    lib.MXTNDArraySave.argtypes = [ctypes.c_char_p, u32, vpp, ccp]
    lib.MXTNDArrayLoad.argtypes = [ctypes.c_char_p, u32p,
                                   ctypes.POINTER(vpp), u32p,
                                   ctypes.POINTER(ccp)]
    lib.MXTNDArrayFromData.argtypes = [i64p, u32, ctypes.c_int, vp,
                                       ctypes.c_size_t, vpp]
    lib.MXTNDArraySyncCopyToCPU.argtypes = [vp, vp, ctypes.c_size_t]
    lib.MXTNDArraySyncCopyFromCPU.argtypes = [vp, vp, ctypes.c_size_t]
    lib.MXTNDArrayFree.argtypes = [vp]
    lib.MXTListAllOpNames.argtypes = [u32p, ctypes.POINTER(ccp)]
    lib.MXTGetVersion.argtypes = [ctypes.POINTER(ctypes.c_int)]
    return lib


def _ck(lib, rc):
    assert rc == 0, lib.MXTGetLastError().decode()


def _nd_from(lib, arr):
    arr = onp.ascontiguousarray(arr, "float32")
    h = ctypes.c_void_p()
    shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
    _ck(lib, lib.MXTNDArrayFromData(
        shape, arr.ndim, 0, arr.ctypes.data_as(ctypes.c_void_p),
        arr.nbytes, ctypes.byref(h)))
    return h


def _to_np(lib, h, shape):
    out = onp.empty(shape, "float32")
    _ck(lib, lib.MXTNDArraySyncCopyToCPU(
        h, out.ctypes.data_as(ctypes.c_void_p), out.nbytes))
    return out


class TestSymbolABI:
    def test_json_round_trip(self, lib):
        json_str = _mlp_symbol().tojson().encode()
        h = ctypes.c_void_p()
        _ck(lib, lib.MXTSymbolCreateFromJSON(json_str, ctypes.byref(h)))
        n = ctypes.c_uint32()
        names = ctypes.POINTER(ctypes.c_char_p)()
        _ck(lib, lib.MXTSymbolListArguments(h, ctypes.byref(n),
                                            ctypes.byref(names)))
        args = [names[i].decode() for i in range(n.value)]
        assert "data" in args and "fc1_weight" in args
        out = ctypes.c_char_p()
        _ck(lib, lib.MXTSymbolSaveToJSON(h, ctypes.byref(out)))
        sym2 = mx.sym.load_json(out.value.decode())
        assert sym2.list_arguments() == _mlp_symbol().list_arguments()
        lib.MXTSymbolFree(h)

    def test_atomic_compose(self, lib):
        # variable -> atomic relu -> compose, positionally
        v = ctypes.c_void_p()
        _ck(lib, lib.MXTSymbolCreateVariable(b"x", ctypes.byref(v)))
        atom = ctypes.c_void_p()
        _ck(lib, lib.MXTSymbolCreateAtomicSymbol(
            b"relu", 0, None, None, ctypes.byref(atom)))
        args = (ctypes.c_void_p * 1)(v)
        composed = ctypes.c_void_p()
        _ck(lib, lib.MXTSymbolCompose(atom, b"act0", 1, None, args,
                                      ctypes.byref(composed)))
        n = ctypes.c_uint32()
        names = ctypes.POINTER(ctypes.c_char_p)()
        _ck(lib, lib.MXTSymbolListOutputs(composed, ctypes.byref(n),
                                          ctypes.byref(names)))
        assert n.value == 1
        for h in (v, atom, composed):
            lib.MXTSymbolFree(h)

    def test_infer_shape(self, lib):
        json_str = _mlp_symbol().tojson().encode()
        h = ctypes.c_void_p()
        _ck(lib, lib.MXTSymbolCreateFromJSON(json_str, ctypes.byref(h)))
        names = (ctypes.c_char_p * 2)(b"data", b"label")
        ndims = (ctypes.c_uint32 * 2)(2, 2)
        flat = (ctypes.c_int64 * 4)(8, 4, 8, 1)
        argc = ctypes.c_uint32()
        outc = ctypes.c_uint32()
        auxc = ctypes.c_uint32()
        all_nd = ctypes.POINTER(ctypes.c_uint32)()
        all_d = ctypes.POINTER(ctypes.c_int64)()
        _ck(lib, lib.MXTSymbolInferShape(
            h, 2, names, ndims, flat, ctypes.byref(argc),
            ctypes.byref(outc), ctypes.byref(auxc), ctypes.byref(all_nd),
            ctypes.byref(all_d)))
        assert outc.value == 1
        # first arg is data: (8, 4)
        assert all_nd[0] == 2 and all_d[0] == 8 and all_d[1] == 4
        lib.MXTSymbolFree(h)


class TestExecutorABI:
    def test_forward_backward(self, lib):
        json_str = _mlp_symbol().tojson().encode()
        sym = ctypes.c_void_p()
        _ck(lib, lib.MXTSymbolCreateFromJSON(json_str, ctypes.byref(sym)))
        names = (ctypes.c_char_p * 2)(b"data", b"label")
        ndims = (ctypes.c_uint32 * 2)(2, 2)
        flat = (ctypes.c_int64 * 4)(4, 3, 4, 1)
        ex = ctypes.c_void_p()
        _ck(lib, lib.MXTExecutorSimpleBind(sym, 2, names, ndims, flat,
                                           b"write", ctypes.byref(ex)))
        data = ctypes.c_void_p()
        _ck(lib, lib.MXTExecutorArgArray(ex, b"data", ctypes.byref(data)))
        x = onp.ones((4, 3), "float32")
        _ck(lib, lib.MXTNDArraySyncCopyFromCPU(
            data, x.ctypes.data_as(ctypes.c_void_p), x.nbytes))
        _ck(lib, lib.MXTExecutorForward(ex, 1))
        nout = ctypes.c_uint32()
        outs = (ctypes.c_void_p * 2)()
        _ck(lib, lib.MXTExecutorOutputs(ex, ctypes.byref(nout), outs, 2))
        assert nout.value == 1
        _ck(lib, lib.MXTExecutorBackward(ex, 0, None))
        g = ctypes.c_void_p()
        _ck(lib, lib.MXTExecutorGradArray(ex, b"fc1_weight",
                                          ctypes.byref(g)))
        gv = _to_np(lib, g, (1, 3))
        assert onp.all(onp.isfinite(gv))
        for h in (data, outs[0], g):
            lib.MXTNDArrayFree(h)
        lib.MXTExecutorFree(ex)
        lib.MXTSymbolFree(sym)


class TestKVStoreABI:
    def test_int_key_push_pull(self, lib):
        kv = ctypes.c_void_p()
        _ck(lib, lib.MXTKVStoreCreate(b"local", ctypes.byref(kv)))
        t = ctypes.c_char_p()
        _ck(lib, lib.MXTKVStoreGetType(kv, ctypes.byref(t)))
        assert t.value == b"local"
        r = ctypes.c_int()
        _ck(lib, lib.MXTKVStoreGetRank(kv, ctypes.byref(r)))
        assert r.value == 0
        a = _nd_from(lib, onp.full((2, 2), 3.0))
        _ck(lib, lib.MXTKVStoreInit(kv, 7, a))
        b = _nd_from(lib, onp.full((2, 2), 2.0))
        _ck(lib, lib.MXTKVStorePush(kv, 7, b, 0))
        out = _nd_from(lib, onp.zeros((2, 2)))
        _ck(lib, lib.MXTKVStorePull(kv, 7, out, 0))
        onp.testing.assert_allclose(_to_np(lib, out, (2, 2)), 2.0)
        for h in (a, b, out):
            lib.MXTNDArrayFree(h)
        lib.MXTKVStoreFree(kv)


class TestDataIterABI:
    def test_csv_iter(self, lib, tmp_path):
        csv = tmp_path / "d.csv"
        onp.savetxt(csv, onp.arange(12, dtype="float32").reshape(6, 2),
                    delimiter=",")
        keys = (ctypes.c_char_p * 3)(b"data_csv", b"data_shape",
                                     b"batch_size")
        vals = (ctypes.c_char_p * 3)(str(csv).encode(), b"(2,)", b"3")
        it = ctypes.c_void_p()
        _ck(lib, lib.MXTDataIterCreate(b"CSVIter", 3, keys, vals,
                                       ctypes.byref(it)))
        more = ctypes.c_int()
        _ck(lib, lib.MXTDataIterNext(it, ctypes.byref(more)))
        assert more.value == 1
        d = ctypes.c_void_p()
        _ck(lib, lib.MXTDataIterGetData(it, ctypes.byref(d)))
        onp.testing.assert_allclose(
            _to_np(lib, d, (3, 2)),
            onp.arange(6, dtype="float32").reshape(3, 2))
        lib.MXTNDArrayFree(d)
        lib.MXTDataIterFree(it)


class TestSaveLoadABI:
    def test_named_round_trip(self, lib, tmp_path):
        f = str(tmp_path / "w.params").encode()
        a = _nd_from(lib, onp.arange(4, dtype="float32").reshape(2, 2))
        handles = (ctypes.c_void_p * 1)(a)
        names = (ctypes.c_char_p * 1)(b"arg:w0")
        _ck(lib, lib.MXTNDArraySave(f, 1, handles, names))
        # load through the ABI
        n = ctypes.c_uint32()
        arrs = ctypes.POINTER(ctypes.c_void_p)()
        nn = ctypes.c_uint32()
        onames = ctypes.POINTER(ctypes.c_char_p)()
        _ck(lib, lib.MXTNDArrayLoad(f, ctypes.byref(n),
                                    ctypes.byref(arrs), ctypes.byref(nn),
                                    ctypes.byref(onames)))
        assert n.value == 1 and nn.value == 1
        assert onames[0] == b"arg:w0"
        onp.testing.assert_allclose(
            _to_np(lib, arrs[0], (2, 2)),
            onp.arange(4, dtype="float32").reshape(2, 2))
        lib.MXTNDArrayFree(arrs[0])
        # and through Python (byte-format compat)
        loaded = mx.nd.load(f.decode())
        assert list(loaded) == ["arg:w0"]
        lib.MXTNDArrayFree(a)


class TestMisc:
    def test_version_and_ops(self, lib):
        v = ctypes.c_int()
        _ck(lib, lib.MXTGetVersion(ctypes.byref(v)))
        assert v.value == 10600
        n = ctypes.c_uint32()
        names = ctypes.POINTER(ctypes.c_char_p)()
        _ck(lib, lib.MXTListAllOpNames(ctypes.byref(n),
                                       ctypes.byref(names)))
        ops = {names[i] for i in range(n.value)}
        assert n.value > 400 and b"FullyConnected" in ops


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_cpp_lenet_trains(tmp_path):
    """cpp-package parity criterion: the C++ LeNet example (Symbol::
    CreateOp graph, Xavier init, SGD+momentum optimizer, FactorScheduler,
    Accuracy metric, checkpoint save/load) compiles and trains to >=0.9
    accuracy (ref: cpp-package/example/lenet.cpp)."""
    if _build_lib() is None:
        pytest.skip("frontier C ABI not built")
    exe = str(tmp_path / "train_lenet")
    subprocess.run(
        ["g++", "-O2", "-std=c++17",
         "-I", os.path.join(REPO, "cpp-package", "include"),
         os.path.join(REPO, "cpp-package", "example", "train_lenet.cpp"),
         "-o", exe,
         "-L" + os.path.join(REPO, "mxnet_tpu"), "-lmxnet_tpu",
         "-Wl,-rpath," + os.path.join(REPO, "mxnet_tpu")],
        check=True, capture_output=True)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    res = subprocess.run([exe], env=env, capture_output=True, text=True,
                         timeout=600, cwd=str(tmp_path))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "cpp-package LeNet training OK" in res.stdout
    # the checkpoint the C++ program wrote loads in Python
    params = mx.nd.load(str(tmp_path / "lenet.params"))
    assert "conv1_weight" in params and "fc2_bias" in params


@pytest.mark.skipif(shutil.which("gcc") is None, reason="no gcc")
def test_c_demo_trains_symbol_from_json(tmp_path):
    """The done-criterion: pure-C program loads symbol JSON, trains via
    DataIter+KVStore, saves a checkpoint Python verifies."""
    if _build_lib() is None:
        pytest.skip("frontier C ABI not built")
    rng = onp.random.RandomState(0)
    w_true = onp.array([[1.5], [-2.0], [0.5], [3.0]], "float32")
    X = rng.randn(64, 4).astype("float32")
    y = X @ w_true + 0.7
    onp.savetxt(tmp_path / "data.csv", X, delimiter=",")
    onp.savetxt(tmp_path / "label.csv", y, delimiter=",")
    _mlp_symbol().save(str(tmp_path / "sym.json"))

    exe = str(tmp_path / "train_symbol")
    subprocess.run(
        ["gcc", "-O2", DEMO, "-o", exe,
         "-L" + os.path.join(REPO, "mxnet_tpu"), "-lmxnet_tpu",
         "-Wl,-rpath," + os.path.join(REPO, "mxnet_tpu")],
        check=True, capture_output=True)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    ckpt = str(tmp_path / "trained.params")
    res = subprocess.run(
        [exe, str(tmp_path / "sym.json"), str(tmp_path / "data.csv"),
         str(tmp_path / "label.csv"), ckpt],
        env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    losses = [float(ln.rsplit(" ", 1)[1])
              for ln in res.stdout.splitlines() if ln.startswith("epoch")]
    assert losses[-1] < losses[0] * 0.05, res.stdout

    # Python loads the C-written checkpoint and reproduces the fit
    params = mx.nd.load(ckpt)
    assert set(params) == {"fc1_weight", "fc1_bias"}
    w = params["fc1_weight"].asnumpy()
    b = params["fc1_bias"].asnumpy()
    pred = X @ w.T + b
    assert onp.mean((pred - y) ** 2) < 0.1


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_generated_op_h_compiles_and_runs(tmp_path):
    """The generated per-op C++ wrappers (cpp-package op.h, the
    OpWrapperGenerator analog — 460+ named functions) compile and
    drive a softmax net end to end."""
    if _build_lib() is None:
        pytest.skip("frontier C ABI not built")
    exe = str(tmp_path / "op_h_smoke")
    cc = subprocess.run(
        ["g++", "-O2", "-std=c++17",
         "-I", os.path.join(REPO, "cpp-package", "include"),
         os.path.join(REPO, "cpp-package", "example", "op_h_smoke.cpp"),
         "-o", exe,
         "-L" + os.path.join(REPO, "mxnet_tpu"), "-lmxnet_tpu",
         "-Wl,-rpath," + os.path.join(REPO, "mxnet_tpu")],
        capture_output=True, text=True)
    assert cc.returncode == 0, cc.stderr
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    res = subprocess.run([exe], env=env, capture_output=True, text=True,
                         timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "op.h wrappers OK" in res.stdout


def test_op_h_is_current():
    """The checked-in generated header matches the registry BOTH ways
    (run cpp-package/scripts/gen_op_h.py after op changes). The
    expected set is computed in a FRESH interpreter — the in-process
    registry may carry ops other test modules registered dynamically
    (plugins, fused subgraph regions), which the generator never
    sees."""
    import importlib.util
    import re
    spec = importlib.util.spec_from_file_location(
        "gen_op_h", os.path.join(REPO, "cpp-package", "scripts",
                                 "gen_op_h.py"))
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)
    hdr = open(os.path.join(REPO, "cpp-package", "include",
                            "mxnet_tpu-cpp", "op.h")).read()
    declared = set(re.findall(r'Symbol::CreateOp\("([^"]+)"', hdr))
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-c",
         "from mxnet_tpu.ops import registry as r;"
         "print('\\n'.join(r.list_ops()))"],
        env=env, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr
    clean_names = res.stdout.split()
    expected = {n for n in clean_names if gen._cpp_name(n) is not None}
    missing = sorted(expected - declared)
    stale = sorted(declared - expected)
    assert not missing, "op.h is stale; regenerate. Missing: %s" \
        % missing[:10]
    assert not stale, "op.h has wrappers for removed ops: %s" % stale[:10]


class TestRound3Additions:
    """Views, autograd flags, profiler controls, symbol attrs
    (ref: MXNDArrayReshape/Slice/At, MXAutogradIsRecording/IsTraining,
    MXSetProcessProfilerConfig/State + MXDumpProfile,
    MXSymbolGetAttr/SetAttr/ListAttr/GetInternals/GetOutput/Copy)."""

    def test_ndarray_views(self, lib):
        vp = ctypes.c_void_p
        lib.MXTNDArrayReshape.argtypes = [vp, ctypes.c_uint32,
                                          ctypes.POINTER(ctypes.c_int64),
                                          ctypes.POINTER(vp)]
        lib.MXTNDArraySlice.argtypes = [vp, ctypes.c_int64,
                                        ctypes.c_int64,
                                        ctypes.POINTER(vp)]
        lib.MXTNDArrayAt.argtypes = [vp, ctypes.c_int64,
                                     ctypes.POINTER(vp)]
        a = _nd_from(lib, onp.arange(12, dtype="float32").reshape(3, 4))
        r = ctypes.c_void_p()
        dims = (ctypes.c_int64 * 2)(4, 3)
        _ck(lib, lib.MXTNDArrayReshape(a, 2, dims, ctypes.byref(r)))
        onp.testing.assert_allclose(
            _to_np(lib, r, (4, 3)).ravel(), onp.arange(12))
        s = ctypes.c_void_p()
        _ck(lib, lib.MXTNDArraySlice(a, 1, 3, ctypes.byref(s)))
        onp.testing.assert_allclose(_to_np(lib, s, (2, 4))[0, 0], 4.0)
        at = ctypes.c_void_p()
        _ck(lib, lib.MXTNDArrayAt(a, 2, ctypes.byref(at)))
        onp.testing.assert_allclose(_to_np(lib, at, (4,))[0], 8.0)
        for h in (a, r, s, at):
            lib.MXTNDArrayFree(h)

    def test_autograd_flags(self, lib):
        rec = ctypes.c_int(-1)
        _ck(lib, lib.MXTAutogradIsRecording(ctypes.byref(rec)))
        assert rec.value == 0
        _ck(lib, lib.MXTAutogradSetIsTraining(1))
        tr = ctypes.c_int(-1)
        _ck(lib, lib.MXTAutogradIsTraining(ctypes.byref(tr)))
        assert tr.value == 1
        _ck(lib, lib.MXTAutogradSetIsTraining(0))

    def test_profiler_controls(self, lib, tmp_path):
        ccp = ctypes.POINTER(ctypes.c_char_p)
        out = str(tmp_path / "c_profile.json")
        keys = (ctypes.c_char_p * 1)(b"filename")
        vals = (ctypes.c_char_p * 1)(out.encode())
        _ck(lib, lib.MXTProfileSetConfig(1, keys, vals))
        _ck(lib, lib.MXTProfileSetState(1))
        h = _nd_from(lib, onp.ones((2, 2), "float32"))
        lib.MXTNDArrayFree(h)
        _ck(lib, lib.MXTProfileSetState(0))
        _ck(lib, lib.MXTProfileDump())
        assert os.path.exists(out)

    def test_symbol_attrs_and_views(self, lib):
        vp = ctypes.c_void_p
        ccp = ctypes.POINTER(ctypes.c_char_p)
        lib.MXTSymbolGetAttr.argtypes = [vp, ctypes.c_char_p, ccp,
                                         ctypes.POINTER(ctypes.c_int)]
        lib.MXTSymbolSetAttr.argtypes = [vp, ctypes.c_char_p,
                                         ctypes.c_char_p]
        lib.MXTSymbolListAttr.argtypes = [vp, ccp]
        lib.MXTSymbolGetInternals.argtypes = [vp, ctypes.POINTER(vp)]
        lib.MXTSymbolGetOutput.argtypes = [vp, ctypes.c_uint32,
                                           ctypes.POINTER(vp)]
        lib.MXTSymbolCopy.argtypes = [vp, ctypes.POINTER(vp)]
        h = ctypes.c_void_p()
        _ck(lib, lib.MXTSymbolCreateFromJSON(
            _mlp_symbol().tojson().encode(), ctypes.byref(h)))
        _ck(lib, lib.MXTSymbolSetAttr(h, b"lr_mult", b"2.0"))
        out = ctypes.c_char_p()
        ok = ctypes.c_int()
        _ck(lib, lib.MXTSymbolGetAttr(h, b"lr_mult", ctypes.byref(out),
                                      ctypes.byref(ok)))
        assert ok.value == 1 and out.value == b"2.0"
        # empty string is PRESENT; a missing key is success=0
        _ck(lib, lib.MXTSymbolSetAttr(h, b"note", b""))
        _ck(lib, lib.MXTSymbolGetAttr(h, b"note", ctypes.byref(out),
                                      ctypes.byref(ok)))
        assert ok.value == 1 and out.value == b""
        _ck(lib, lib.MXTSymbolGetAttr(h, b"nope", ctypes.byref(out),
                                      ctypes.byref(ok)))
        assert ok.value == 0
        attrs_json = ctypes.c_char_p()
        _ck(lib, lib.MXTSymbolListAttr(h, ctypes.byref(attrs_json)))
        import json as _json
        assert isinstance(_json.loads(attrs_json.value.decode()), dict)
        internals = ctypes.c_void_p()
        _ck(lib, lib.MXTSymbolGetInternals(h, ctypes.byref(internals)))
        n = ctypes.c_uint32()
        names = ccp()
        _ck(lib, lib.MXTSymbolListOutputs(internals, ctypes.byref(n),
                                          ctypes.byref(names)))
        assert n.value > 1  # every internal node is an output
        out0 = ctypes.c_void_p()
        _ck(lib, lib.MXTSymbolGetOutput(h, 0, ctypes.byref(out0)))
        cp = ctypes.c_void_p()
        _ck(lib, lib.MXTSymbolCopy(h, ctypes.byref(cp)))
        for x in (h, internals, out0, cp):
            lib.MXTSymbolFree(x)


@pytest.mark.skipif(shutil.which("gcc") is None, reason="no gcc")
def test_c_demo_cachedop_cache_hits(tmp_path):
    """VERDICT r4 done-criterion: a C caller drives the jit seam —
    second same-signature invoke hits the compile cache, a resized
    input recompiles (example/capi/cachedop_demo.c)."""
    if _build_lib() is None:
        pytest.skip("frontier C ABI not built")
    data = mx.sym.var("data")
    w = mx.sym.var("w")
    net = mx.sym.FullyConnected(data, weight=w, num_hidden=2,
                                no_bias=True, name="fc")
    net.save(str(tmp_path / "sym.json"))
    demo = os.path.join(REPO, "example", "capi", "cachedop_demo.c")
    exe = str(tmp_path / "cachedop_demo")
    subprocess.run(
        ["gcc", "-O2", demo, "-o", exe,
         "-L" + os.path.join(REPO, "mxnet_tpu"), "-lmxnet_tpu",
         "-Wl,-rpath," + os.path.join(REPO, "mxnet_tpu")],
        check=True, capture_output=True)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    res = subprocess.run([exe, str(tmp_path / "sym.json")], env=env,
                         capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "calls=2 compiles=1" in res.stdout
    assert "calls=3 compiles=2" in res.stdout
    assert "CachedOp C ABI OK" in res.stdout
