"""Worker for the 2-process straggler-detection test (ISSUE 8
acceptance): rank 1 runs each step ~9x slower than rank 0 (an injected
per-rank delay). Every v1 heartbeat carries the rank's newest completed
step duration (the watchdog beacon), so the PS server's
``metrics()['kvstore_server']`` must name rank 1 in ``stragglers``
without any extra wire round trip — which both ranks verify by pulling
``kv.server_metrics()``.

Run via: python tools/launch.py -n 2 python tests/flightrec_straggler_worker.py
"""
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu._debug import watchdog  # noqa: E402


def main():
    rank = int(os.environ["MXTPU_PROC_ID"])
    kv = mx.kv.create("dist_async")
    kv.init("w", mx.nd.zeros((8,)))
    delay = 0.45 if rank == 1 else 0.05

    ks = {}
    deadline = time.time() + 120
    while time.time() < deadline:
        watchdog.step_begin()
        kv.push("w", mx.nd.ones((8,)))
        out = mx.nd.zeros((8,))
        kv.pull("w", out=out)
        time.sleep(delay)  # the injected per-rank step-time skew
        watchdog.step_end()
        ks = kv.server_metrics()[0].get("kvstore_server", {})
        if ks.get("stragglers") == [1] \
                and "rank_step_s.0" in ks and "rank_step_s.1" in ks:
            break
    assert ks.get("stragglers") == [1], \
        "server never named rank 1 as the straggler: %r" % (ks,)
    assert ks["straggler.1"] == 1 and "straggler.0" not in ks, ks
    assert ks["step_skew.1"] > 2.0 > ks["step_skew.0"], ks
    assert ks["rank_step_s.1"] > ks["rank_step_s.0"] > 0, ks
    print("rank %d: STRAGGLER_OK" % rank, flush=True)

    kv._barrier()
    if rank == 0:
        kv.close()
    else:
        kv.done()


if __name__ == "__main__":
    main()
