"""Module + Executor tests, mirroring the reference's
tests/python/unittest/test_module.py and test_executor.py strategy:
bind/fit/score round trips, checkpoint format, bucketing, input grads.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _mlp_symbol(num_hidden=32, num_classes=4):
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    fc1 = sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    act1 = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act1, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(fc2, label, name="softmax")


def _toy_data(n=256, dim=10, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.uniform(-1, 1, (dim, classes))
    x = rng.uniform(-1, 1, (n, dim)).astype("float32")
    y = np.argmax(x @ w + 0.1 * rng.randn(n, classes), axis=1)
    return x, y.astype("float32")


class TestExecutor:
    def test_simple_bind_forward_backward(self):
        out = _mlp_symbol()
        exe = out.simple_bind(mx.cpu(), data=(8, 10), softmax_label=(8,))
        assert set(exe.arg_dict) == {"data", "fc1_weight", "fc1_bias",
                                     "fc2_weight", "fc2_bias",
                                     "softmax_label"}
        rng = np.random.RandomState(0)
        for n, arr in exe.arg_dict.items():
            if n not in ("data", "softmax_label"):
                arr._data = arr._data + rng.uniform(
                    -0.1, 0.1, arr.shape).astype("float32")
        x = rng.uniform(size=(8, 10)).astype("float32")
        y = rng.randint(0, 4, size=(8,)).astype("float32")
        outs = exe.forward(is_train=True, data=x, softmax_label=y)
        p = outs[0].asnumpy()
        assert p.shape == (8, 4)
        np.testing.assert_allclose(p.sum(axis=1), np.ones(8), rtol=1e-5)
        exe.backward()
        # SoftmaxOutput backward: dfc2 = softmax - onehot
        g = exe.grad_dict["fc2_bias"].asnumpy()
        onehot = np.eye(4)[y.astype(int)]
        np.testing.assert_allclose(g, (p - onehot).sum(axis=0), rtol=1e-4,
                                   atol=1e-5)

    def test_grad_req_add_and_null(self):
        data = sym.Variable("data")
        out = sym.FullyConnected(data, num_hidden=3, name="fc")
        exe = out.simple_bind(mx.cpu(), grad_req="add", data=(2, 5))
        rng = np.random.RandomState(0)
        exe.arg_dict["fc_weight"]._data = exe.arg_dict["fc_weight"]._data + \
            rng.uniform(size=(3, 5)).astype("float32")
        x = rng.uniform(size=(2, 5)).astype("float32")
        exe.forward(is_train=True, data=x)
        exe.backward()
        g1 = exe.grad_dict["fc_weight"].asnumpy().copy()
        exe.forward(is_train=True, data=x)
        exe.backward()
        g2 = exe.grad_dict["fc_weight"].asnumpy()
        np.testing.assert_allclose(g2, 2 * g1, rtol=1e-5)

    def test_executor_reshape(self):
        out = _mlp_symbol()
        exe = out.simple_bind(mx.cpu(), data=(8, 10), softmax_label=(8,))
        exe2 = exe.reshape(data=(4, 10), softmax_label=(4,))
        assert exe2.arg_dict["data"].shape == (4, 10)
        # params shared
        assert exe2.arg_dict["fc1_weight"] is exe.arg_dict["fc1_weight"]
        x = np.random.uniform(size=(4, 10)).astype("float32")
        y = np.zeros((4,), "float32")
        outs = exe2.forward(is_train=False, data=x, softmax_label=y)
        assert outs[0].shape == (4, 4)

    def test_symbol_json_roundtrip_exec(self, tmp_path):
        out = _mlp_symbol()
        f = str(tmp_path / "net-symbol.json")
        out.save(f)
        out2 = sym.load(f)
        assert out2.list_arguments() == out.list_arguments()
        exe = out2.simple_bind(mx.cpu(), data=(2, 10), softmax_label=(2,))
        exe.forward(is_train=False,
                    data=np.zeros((2, 10), "float32"),
                    softmax_label=np.zeros((2,), "float32"))

    def test_eval(self):
        a = sym.Variable("a")
        b = sym.Variable("b")
        c = a + 2.0 * b
        exe = c.bind(mx.cpu(), args={"a": mx.nd.array([1.0, 2.0]),
                                     "b": mx.nd.array([2.0, 3.0])})
        out = exe.forward()[0].asnumpy()
        np.testing.assert_allclose(out, [5.0, 8.0], rtol=1e-6)


class TestModule:
    def test_bind_init_forward(self):
        mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
        mod.bind(data_shapes=[("data", (16, 10))],
                 label_shapes=[("softmax_label", (16,))])
        mod.init_params(initializer=mx.init.Xavier())
        assert mod.binded and mod.params_initialized
        batch = mx.io.DataBatch(
            data=[mx.nd.array(np.random.uniform(size=(16, 10)))],
            label=[mx.nd.array(np.zeros((16,)))])
        mod.forward(batch, is_train=False)
        out = mod.get_outputs()[0]
        assert out.shape == (16, 4)

    def test_fit_accuracy(self):
        x, y = _toy_data()
        train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True)
        val = mx.io.NDArrayIter(x, y, batch_size=32)
        mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
        # lr under CORRECT 1/batch_size gradient normalization
        # (ref: module.py init_optimizer rescale_grad default)
        mod.fit(train, eval_data=val, optimizer="sgd",
                optimizer_params={"learning_rate": 1.0, "momentum": 0.9},
                initializer=mx.init.Xavier(),
                eval_metric="acc", num_epoch=12)
        score = mod.score(val, "acc")
        assert score[0][1] > 0.85, score

    def test_module_input_grads(self):
        mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
        mod.bind(data_shapes=[("data", (4, 10))],
                 label_shapes=[("softmax_label", (4,))],
                 inputs_need_grad=True)
        mod.init_params()
        batch = mx.io.DataBatch(
            data=[mx.nd.array(np.random.uniform(size=(4, 10)))],
            label=[mx.nd.array(np.zeros((4,)))])
        mod.forward(batch, is_train=True)
        mod.backward()
        [dgrad] = mod.get_input_grads()
        assert dgrad is not None and dgrad.shape == (4, 10)
        assert float(np.abs(dgrad.asnumpy()).sum()) > 0

    def test_checkpoint_roundtrip(self, tmp_path):
        x, y = _toy_data(n=64)
        train = mx.io.NDArrayIter(x, y, batch_size=32)
        mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
        mod.fit(train, num_epoch=2,
                optimizer_params={"learning_rate": 0.1})
        prefix = str(tmp_path / "mlp")
        mod.save_checkpoint(prefix, 2, save_optimizer_states=True)
        assert os.path.exists(prefix + "-symbol.json")
        assert os.path.exists(prefix + "-0002.params")
        assert os.path.exists(prefix + "-0002.states")

        mod2 = mx.mod.Module.load(prefix, 2, load_optimizer_states=True)
        mod2.bind(data_shapes=[("data", (32, 10))],
                  label_shapes=[("softmax_label", (32,))])
        mod2.init_optimizer()
        batch = mx.io.DataBatch(data=[mx.nd.array(x[:32])],
                                label=[mx.nd.array(y[:32])])
        mod.forward(batch, is_train=False)
        mod2.forward(batch, is_train=False)
        np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                                   mod2.get_outputs()[0].asnumpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_model_save_load_checkpoint_helpers(self, tmp_path):
        from mxnet_tpu.model import save_checkpoint, load_checkpoint
        s = _mlp_symbol()
        arg = {"fc1_weight": mx.nd.array(np.ones((32, 10)))}
        aux = {}
        prefix = str(tmp_path / "m")
        save_checkpoint(prefix, 7, s, arg, aux)
        s2, arg2, aux2 = load_checkpoint(prefix, 7)
        assert s2.list_arguments() == s.list_arguments()
        np.testing.assert_allclose(arg2["fc1_weight"].asnumpy(),
                                   np.ones((32, 10)))

    def test_multi_context_data_parallel(self):
        """DP over several contexts = one GSPMD-sharded executor; numerics
        must match single-device."""
        x, y = _toy_data(n=64)
        batch = mx.io.DataBatch(data=[mx.nd.array(x[:32])],
                                label=[mx.nd.array(y[:32])])
        outs = []
        for ctxs in ([mx.cpu(0)], [mx.cpu(0), mx.cpu(1)]):
            mod = mx.mod.Module(_mlp_symbol(), context=ctxs)
            mod.bind(data_shapes=[("data", (32, 10))],
                     label_shapes=[("softmax_label", (32,))])
            mod.init_params(initializer=mx.init.One())
            mod.forward(batch, is_train=False)
            outs.append(mod.get_outputs()[0].asnumpy())
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)

    def test_reshape_on_batch_change(self):
        mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
        mod.bind(data_shapes=[("data", (16, 10))],
                 label_shapes=[("softmax_label", (16,))])
        mod.init_params()
        small = mx.io.DataBatch(
            data=[mx.nd.array(np.zeros((8, 10), "float32"))],
            label=[mx.nd.array(np.zeros((8,), "float32"))])
        mod.forward(small, is_train=False)
        assert mod.get_outputs()[0].shape == (8, 4)


class TestBucketingModule:
    def test_bucketing_fit(self):
        """Variable-length sequences via buckets (ref:
        tests/python/train/test_bucketing.py shape)."""
        buckets = [8, 16]
        num_classes = 3

        def sym_gen(seq_len):
            data = sym.Variable("data")
            label = sym.Variable("softmax_label")
            pooled = sym.mean(data, axis=1, keepdims=True, name="pool")
            fc = sym.FullyConnected(pooled, num_hidden=num_classes,
                                    name="fc")
            out = sym.SoftmaxOutput(fc, label, name="softmax")
            return out, ("data",), ("softmax_label",)

        mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16,
                                     context=mx.cpu())
        mod.bind(data_shapes=[("data", (4, 16))],
                 label_shapes=[("softmax_label", (4,))])
        mod.init_params()
        mod.init_optimizer(optimizer_params={"learning_rate": 0.1})

        rng = np.random.RandomState(0)
        for seq_len in (16, 8, 16, 8):
            batch = mx.io.DataBatch(
                data=[mx.nd.array(rng.uniform(size=(4, seq_len)))],
                label=[mx.nd.array(rng.randint(0, 3, (4,)))],
                bucket_key=seq_len,
                provide_data=[mx.io.DataDesc("data", (4, seq_len))],
                provide_label=[mx.io.DataDesc("softmax_label", (4,))])
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            assert mod.get_outputs()[0].shape == (4, 3)
        # params are shared across buckets
        assert len(mod._buckets) == 2
        e16 = mod._buckets[16]._exec_group.executor
        e8 = mod._buckets[8]._exec_group.executor
        assert e16.arg_dict["fc_bias"] is e8.arg_dict["fc_bias"]
        assert e16.arg_dict["fc_weight"] is e8.arg_dict["fc_weight"]


def test_init_params_arg_only_initializes_aux():
    """Regression: init_params(arg_params=...) without aux_params must still
    run the initializer on aux states (moving_var -> ones, not zeros)."""
    import numpy as np
    d = mx.sym.Variable("data")
    b = mx.sym.BatchNorm(mx.sym.FullyConnected(d, num_hidden=4), name="bn")
    m = mx.mod.Module(b, label_names=None, context=mx.cpu())
    m.bind([("data", (2, 8))], for_training=False)
    m.init_params()
    args, _ = m.get_params()
    m2 = mx.mod.Module(b, label_names=None, context=mx.cpu())
    m2.bind([("data", (2, 8))], for_training=False)
    m2.init_params(arg_params=dict(args))
    _, aux = m2.get_params()
    assert np.allclose(aux["bn_moving_var"].asnumpy(), 1.0)
    assert np.allclose(aux["bn_moving_mean"].asnumpy(), 0.0)


def test_deferred_forward_matches_backward_outputs():
    """Regression: outputs observed after forward(is_train=True) must match
    the outputs backward() recomputes (same PRNG key; one fused program)."""
    import numpy as np
    d = mx.sym.Variable("data")
    net = mx.sym.Dropout(mx.sym.FullyConnected(d, num_hidden=8), p=0.5)
    exe = net.simple_bind(mx.cpu(), data=(4, 6))
    x = np.random.RandomState(3).randn(4, 6).astype("float32")
    outs = exe.forward(is_train=True, data=x)
    o1 = outs[0].asnumpy()
    exe.backward()
    o2 = exe.outputs[0].asnumpy()
    np.testing.assert_allclose(o1, o2, rtol=1e-5, atol=1e-5)


def test_reshape_caches_executors():
    """Regression: alternating batch geometries must reuse cached executor
    groups instead of rebinding/retracing each flip."""
    d = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(d, num_hidden=4)
    m = mx.mod.Module(net, label_names=None, context=mx.cpu())
    m.bind([("data", (8, 6))], for_training=False)
    m.init_params()
    g_a = m._exec_group
    m.reshape([("data", (5, 6))])
    g_b = m._exec_group
    assert g_b is not g_a
    m.reshape([("data", (8, 6))])
    assert m._exec_group is g_a
    m.reshape([("data", (5, 6))])
    assert m._exec_group is g_b


def test_feedforward_eval_tuple_and_callbacks():
    """Regression: FeedForward.fit must accept eval_data=(X, y) and forward
    eval/batch callbacks to Module.fit."""
    import numpy as np
    x = np.random.RandomState(0).randn(40, 8).astype("float32")
    y = (x.sum(1) > 0).astype("float32")
    data = mx.sym.Variable("data")
    lab = mx.sym.Variable("softmax_label")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=2), lab, name="softmax")
    hits = []
    ff = mx.model.FeedForward(net, num_epoch=1)
    ff.fit(x, y, eval_data=(x, y),
           eval_end_callback=lambda *a: hits.append("eval"),
           batch_end_callback=lambda *a: hits.append("batch"))
    assert "eval" in hits and "batch" in hits


def test_bucketing_default_initializer_not_zero():
    """Regression: init_params() with no initializer must apply the default
    Uniform(0.01), not leave weights all-zero."""
    def sym_gen(seq_len):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        fc = sym.FullyConnected(data, num_hidden=3, name="fc")
        out = sym.SoftmaxOutput(fc, label, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    args, _ = mod.get_params()
    assert np.abs(args["fc_weight"].asnumpy()).sum() > 0


def test_init_optimizer_rescales_by_batch_size():
    """Regression: Module must default rescale_grad to 1/batch_size like
    the reference (module.py:498); unnormalized batch-summed gradients
    made sgd+momentum diverge."""
    x, y = _toy_data()
    train = mx.io.NDArrayIter(x, y, batch_size=32)
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    assert abs(mod._optimizer.rescale_grad - 1.0 / 32) < 1e-12
    # explicit user value wins
    mod2 = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label)
    mod2.init_params()
    mod2.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1,
                                          "rescale_grad": 1.0})
    assert mod2._optimizer.rescale_grad == 1.0


def test_group2ctx_model_parallel():
    """group2ctx model parallelism (VERDICT r4 item 7; ref shape:
    example/model-parallel/matrix_factorization/model.py): embedding
    lookups pinned to ctx group dev1, the MLP + inner-product + loss to
    dev2, bound over two devices of the virtual CPU mesh. Cross-group
    edges become device transfers (executor._GraphProgram placement);
    numerics must match a plain single-device bind."""
    import jax

    B, F_, H, MAXU, MAXI = 8, 4, 3, 20, 30
    with mx.AttrScope(ctx_group="dev1"):
        user = mx.sym.Embedding(data=mx.sym.Variable("user"),
                                weight=mx.sym.Variable("user_weight"),
                                input_dim=MAXU, output_dim=F_)
        item = mx.sym.Embedding(data=mx.sym.Variable("item"),
                                weight=mx.sym.Variable("item_weight"),
                                input_dim=MAXI, output_dim=F_)
    with mx.AttrScope(ctx_group="dev2"):
        user = mx.sym.Activation(data=user, act_type="relu")
        user = mx.sym.FullyConnected(
            data=user, weight=mx.sym.Variable("fc_user_weight"),
            bias=mx.sym.Variable("fc_user_bias"), num_hidden=H)
        item = mx.sym.Activation(data=item, act_type="relu")
        item = mx.sym.FullyConnected(
            data=item, weight=mx.sym.Variable("fc_item_weight"),
            bias=mx.sym.Variable("fc_item_bias"), num_hidden=H)
        pred = mx.sym.Flatten(data=mx.sym.sum(user * item, axis=1))
        pred = mx.sym.LinearRegressionOutput(
            data=pred, label=mx.sym.Variable("score"))

    rs = np.random.RandomState(0)
    args = {
        "user": mx.nd.array(rs.randint(0, MAXU, (B,)).astype("float32")),
        "item": mx.nd.array(rs.randint(0, MAXI, (B,)).astype("float32")),
        "user_weight": mx.nd.array(rs.rand(MAXU, F_).astype("float32")),
        "item_weight": mx.nd.array(rs.rand(MAXI, F_).astype("float32")),
        "fc_user_weight": mx.nd.array(rs.rand(H, F_).astype("float32")),
        "fc_user_bias": mx.nd.zeros((H,)),
        "fc_item_weight": mx.nd.array(rs.rand(H, F_).astype("float32")),
        "fc_item_bias": mx.nd.zeros((H,)),
        "score": mx.nd.array(rs.rand(B, 1).astype("float32")),
    }
    grad_names = ["user_weight", "item_weight", "fc_user_weight",
                  "fc_item_weight"]

    def make_grads():
        return {n: mx.nd.zeros(args[n].shape) for n in grad_names}

    g2c = {"dev1": mx.Context("cpu", 1), "dev2": mx.Context("cpu", 2)}
    req = {n: ("write" if n in grad_names else "null") for n in args}
    mp_grads = make_grads()
    exe = pred.bind(mx.cpu(0), args=args, args_grad=mp_grads,
                    grad_req=req, group2ctx=g2c)
    out = exe.forward(is_train=True)
    exe.backward()

    # the head lives in group dev2 -> output committed to cpu device 2
    cpus = jax.local_devices(backend="cpu")
    assert list(out[0]._data.devices()) == [cpus[2]]

    # single-device reference bind: same numbers, forward and backward
    ref_grads = make_grads()
    ref = pred.bind(mx.cpu(0), args=args, args_grad=ref_grads,
                    grad_req=req)
    ref_out = ref.forward(is_train=True)
    ref.backward()
    np.testing.assert_allclose(out[0].asnumpy(), ref_out[0].asnumpy(),
                               rtol=1e-5)
    for n in grad_names:
        np.testing.assert_allclose(mp_grads[n].asnumpy(),
                                   ref_grads[n].asnumpy(), rtol=1e-5)
