"""Extended op long tail (ops/extended.py) vs reference semantics
(ref: src/operator/tensor/*, src/operator/contrib/* — see per-op cites)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_init_ops():
    np.testing.assert_allclose(nd.eye(3).asnumpy(), np.eye(3))
    np.testing.assert_allclose(nd.linspace(0, 1, 5).asnumpy(),
                               np.linspace(0, 1, 5))
    r = nd.invoke_by_name("_arange", start=0, stop=3, repeat=2) \
        if hasattr(nd, "invoke_by_name") else None
    from mxnet_tpu.ndarray.register import invoke_by_name
    r = invoke_by_name("_arange", start=0, stop=3, repeat=2)
    np.testing.assert_allclose(r.asnumpy(), [0, 0, 1, 1, 2, 2])


def test_indexing_utils():
    a = nd.array(np.arange(12).reshape(3, 4).astype("float32"))
    idx = nd.array(np.array([1, 0, 3], "float32"))
    np.testing.assert_allclose(nd.batch_take(a, idx).asnumpy(), [1, 4, 11])
    b = nd.array(np.arange(6).reshape(2, 3).astype("float32"))
    np.testing.assert_allclose(
        nd.reshape_like(nd.array(np.arange(6, dtype="float32")), b)
        .asnumpy().shape, (2, 3))
    parts = nd.split_v2(a, indices=(1,), axis=0)
    assert parts[0].shape == (1, 4) and parts[1].shape == (2, 4)
    flat = nd.ravel_multi_index(
        nd.array(np.array([[0, 1, 2], [3, 2, 1]], "float32")),
        shape=(3, 4))
    np.testing.assert_allclose(flat.asnumpy(), [3, 6, 9])
    back = nd.unravel_index(flat, shape=(3, 4))
    np.testing.assert_allclose(back.asnumpy(), [[0, 1, 2], [3, 2, 1]])


def test_slice_assign():
    from mxnet_tpu.ndarray.register import invoke_by_name
    a = nd.zeros((3, 4))
    r = invoke_by_name("_slice_assign_scalar", a, scalar=5.0,
                       begin=(1, 1), end=(3, 3))
    exp = np.zeros((3, 4), "float32")
    exp[1:3, 1:3] = 5
    np.testing.assert_allclose(r.asnumpy(), exp)


def test_histogram_moments():
    data = nd.array(np.array([0.1, 0.2, 0.2, 0.9], "float32"))
    counts, edges = nd.histogram(data, bin_cnt=2, range=(0.0, 1.0))
    np.testing.assert_allclose(counts.asnumpy(), [3, 1])
    m, v = nd.moments(nd.array(np.array([[1., 2.], [3., 4.]], "float32")),
                      axes=(0,))
    np.testing.assert_allclose(m.asnumpy(), [2, 3])
    np.testing.assert_allclose(v.asnumpy(), [1, 1])


def test_all_finite_and_multi():
    ok = nd.all_finite(nd.array(np.ones(4, "float32")))
    assert float(ok.asnumpy()[0]) == 1.0
    bad = nd.all_finite(nd.array(np.array([1.0, np.inf], "float32")))
    assert float(bad.asnumpy()[0]) == 0.0
    s = nd.multi_sum_sq(nd.array(np.array([1., 2.], "float32")),
                        nd.array(np.array([3.], "float32")), num_arrays=2)
    np.testing.assert_allclose([float(x.asnumpy()) for x in s], [5, 9])


def test_amp_multicast():
    a16 = nd.array(np.ones(2, "float16"))
    a32 = nd.array(np.ones(2, "float32"))
    o1, o2 = nd.amp_multicast(a16, a32, num_outputs=2)
    assert o1.dtype == np.float32 and o2.dtype == np.float32


def test_fft_ifft_roundtrip():
    """Numerics pinned by the reference's check_ifft
    (tests/python/gpu/test_operator_gpu.py:103): ifft is unnormalized."""
    rs = np.random.RandomState(0)
    x = rs.rand(2, 8).astype("float32")
    f = nd.fft(nd.array(x)).asnumpy()
    ref = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(f[:, 0::2], ref.real, atol=1e-4)
    np.testing.assert_allclose(f[:, 1::2], ref.imag, atol=1e-4)
    back = nd.ifft(nd.array(f)).asnumpy()
    np.testing.assert_allclose(back, x * 8, atol=1e-3)


def test_linalg_extras():
    rs = np.random.RandomState(0)
    a = rs.rand(3, 3).astype("float32")
    a = (a + a.T) / 2
    u, lam = nd.linalg_syevd(nd.array(a))
    rec = u.asnumpy().T @ np.diag(lam.asnumpy()) @ u.asnumpy()
    np.testing.assert_allclose(rec, a, atol=1e-4)
    m = nd.array(np.arange(9, dtype="float32").reshape(3, 3))
    tri = nd.linalg_extracttrian(m)
    np.testing.assert_allclose(tri.asnumpy(), [0, 3, 4, 6, 7, 8])
    back = nd.linalg_maketrian(tri)
    np.testing.assert_allclose(back.asnumpy(),
                               np.tril(np.arange(9).reshape(3, 3)))


def test_box_iou():
    a = nd.array(np.array([[0, 0, 2, 2]], "float32"))
    b = nd.array(np.array([[1, 1, 3, 3], [0, 0, 2, 2]], "float32"))
    iou = nd.box_iou(a, b).asnumpy()
    np.testing.assert_allclose(iou, [[1.0 / 7.0, 1.0]], atol=1e-5)


def test_box_nms():
    # records: (score, x1, y1, x2, y2), score_index=0, coord_start=1
    data = np.array([[[0.9, 0, 0, 2, 2],
                      [0.8, 0.1, 0.1, 2, 2],     # overlaps first -> cut
                      [0.7, 5, 5, 6, 6]]], "float32")
    out = nd.box_nms(nd.array(data), overlap_thresh=0.5, coord_start=1,
                     score_index=0).asnumpy()
    assert out[0, 0, 0] == pytest.approx(0.9)
    assert out[0, 1, 0] == pytest.approx(0.7)     # survivor moved up
    assert (out[0, 2] == -1).all()                # suppressed -> -1 row


def test_bipartite_matching():
    # the reference's own docstring example (bounding_box.cc:176)
    x = nd.array(np.array([[0.5, 0.6], [0.1, 0.2], [0.3, 0.4]], "float32"))
    rows, cols = nd.bipartite_matching(x, threshold=1e-12, is_ascend=False)
    np.testing.assert_allclose(rows.asnumpy(), [1, -1, 0])
    np.testing.assert_allclose(cols.asnumpy(), [2, 0])


def test_multibox_prior():
    data = nd.zeros((1, 3, 2, 2))
    anchors = nd.contrib.MultiBoxPrior(data, sizes=(0.5,), ratios=(1.0,)) \
        if hasattr(nd, "contrib") and hasattr(nd.contrib, "MultiBoxPrior") \
        else nd.MultiBoxPrior(data, sizes=(0.5,), ratios=(1.0,))
    a = anchors.asnumpy()
    assert a.shape == (1, 4, 4)
    # centers at (0.25, 0.25), (0.75, 0.25), ... with half-size 0.25
    np.testing.assert_allclose(a[0, 0], [0, 0, 0.5, 0.5], atol=1e-5)


def test_roi_align_and_pooling():
    data = nd.array(np.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    rois = nd.array(np.array([[0, 0, 0, 3, 3]], "float32"))
    out = nd.ROIAlign(data, rois, pooled_size=(2, 2), spatial_scale=1.0)
    assert out.shape == (1, 1, 2, 2)
    assert np.isfinite(out.asnumpy()).all()
    outp = nd.ROIPooling(data, rois, pooled_size=(2, 2), spatial_scale=1.0)
    # max pooling of quantized 2x2 bins over the full 4x4 map
    np.testing.assert_allclose(outp.asnumpy()[0, 0], [[5, 7], [13, 15]])


def test_bilinear_resize_and_adaptive_pool():
    data = nd.array(np.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    up = nd.BilinearResize2D(data, height=8, width=8)
    assert up.shape == (1, 1, 8, 8)
    pooled = nd.AdaptiveAvgPooling2D(data, output_size=(2, 2))
    np.testing.assert_allclose(pooled.asnumpy()[0, 0],
                               [[2.5, 4.5], [10.5, 12.5]])


def test_spatial_transformer_identity():
    rs = np.random.RandomState(0)
    img = rs.rand(1, 1, 5, 5).astype("float32")
    # identity affine
    theta = nd.array(np.array([[1, 0, 0, 0, 1, 0]], "float32"))
    out = nd.SpatialTransformer(nd.array(img), theta, target_shape=(5, 5),
                                transform_type="affine",
                                sampler_type="bilinear")
    np.testing.assert_allclose(out.asnumpy(), img, atol=1e-5)


def test_svm_output_grad():
    from mxnet_tpu import autograd
    x = nd.array(np.array([[2.0, 1.0, 0.1]], "float32"))
    y = nd.array(np.array([0.0], "float32"))
    x.attach_grad()
    with autograd.record():
        out = nd.SVMOutput(x, y, margin=1.0, use_linear=True)
    out.backward()
    g = x.grad.asnumpy()
    # margin violated only by class 1 (2.0 - 1.0 = 1.0, not > margin? equal)
    assert g.shape == (1, 3)


def test_quadratic_and_index_copy():
    x = nd.array(np.array([1.0, 2.0], "float32"))
    np.testing.assert_allclose(
        nd.quadratic(x, a=1, b=2, c=3).asnumpy(), [6, 11])
    t = nd.zeros((4, 2))
    new = nd.array(np.ones((2, 2), "float32"))
    idx = nd.array(np.array([1, 3], "float32"))
    out = nd.index_copy(t, idx, new).asnumpy()
    np.testing.assert_allclose(out[[1, 3]], 1.0)
    np.testing.assert_allclose(out[[0, 2]], 0.0)


def test_legacy_aliases():
    from mxnet_tpu.ops import registry
    for name in ("BatchNorm_v1", "Convolution_v1", "Pooling_v1",
                 "SyncBatchNorm"):
        assert registry.get_op(name) is not None


def test_out_kwarg_writes_in_place():
    """out= must deliver results into the passed NDArray (ref: generated
    wrapper semantics, python/mxnet/_ctypes/ndarray.py)."""
    a = nd.array(np.array([1.0, 2.0], "float32"))
    b = nd.array(np.array([3.0, 4.0], "float32"))
    dest = nd.zeros((2,))
    r = nd.add(a, b, out=dest)
    assert r is dest
    np.testing.assert_allclose(dest.asnumpy(), [4, 6])
