"""Tests for the runtime control-surface modules: profiler, runtime
features, engine, storage, util, jit.

Mirrors coverage from the reference's tests/python/unittest/test_profiler.py,
test_runtime.py, test_engine.py (ref SURVEY.md §4).
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("CPU")
    assert feats.is_enabled("BF16")
    with pytest.raises(RuntimeError):
        feats.is_enabled("NO_SUCH_FEATURE")
    lst = mx.runtime.feature_list()
    assert any(f.name == "CPU" and f.enabled for f in lst)
    assert "CPU" in repr(feats)


def test_profiler_roundtrip(tmp_path):
    from mxnet_tpu import profiler
    fn = str(tmp_path / "profile.json")
    profiler.set_config(filename=fn, aggregate_stats=True)
    profiler.set_state("run")
    assert profiler.is_running()
    profiler.record_op("test_op", 123.0)
    d = profiler.Domain("unit")
    with d.new_task("work"):
        pass
    c = d.new_counter("ctr", 5)
    c += 2
    c -= 1
    d.new_marker("m").mark()
    ev = profiler.Event("ev")
    ev.start()
    ev.stop()
    profiler.pause()
    assert not profiler.is_running()
    profiler.resume()
    profiler.set_state("stop")
    profiler.dump()
    with open(fn) as f:
        trace = json.load(f)
    names = [e["name"] for e in trace["traceEvents"]]
    assert "test_op" in names
    assert "ctr" in names
    table = profiler.dumps()
    assert "test_op" in table


def test_profiler_bad_config():
    from mxnet_tpu import profiler
    with pytest.raises(ValueError):
        profiler.set_config(bogus_key=1)
    with pytest.raises(ValueError):
        profiler.set_state("bogus")


def test_engine_bulk_and_naive():
    from mxnet_tpu import engine
    assert engine.engine_type() == "ThreadedEnginePerDevice"
    prev = engine.set_bulk_size(30)
    assert engine.bulk_size() == 30
    with engine.bulk(5):
        assert engine.bulk_size() == 5
    assert engine.bulk_size() == 30
    engine.set_bulk_size(prev)

    os.environ["MXNET_ENGINE_TYPE"] = "NaiveEngine"
    try:
        assert engine.is_naive()
        a = mx.nd.array([1.0, 2.0])
        engine.maybe_sync(a._data)
    finally:
        del os.environ["MXNET_ENGINE_TYPE"]

    a = mx.nd.array([1.0, 2.0])
    engine.wait_for_var(a)
    engine.wait_for_all()
    assert engine.push_sync(lambda x: x + 1, 1) == 2


def test_storage_stats():
    from mxnet_tpu import storage
    a = mx.nd.zeros((64, 64))
    a.wait_to_read()
    st = storage.stats()
    assert len(st) >= 1
    assert all(s.bytes_in_use >= 0 for s in st)
    assert storage.total_bytes_in_use() >= 0
    storage.release_all()
    repr(st[0])


def test_util_scopes():
    from mxnet_tpu import util
    assert not util.is_np_shape()
    with util.np_shape(True):
        assert util.is_np_shape()
    assert not util.is_np_shape()

    @util.use_np
    def f():
        return util.is_np_array() and util.is_np_shape()
    assert f()
    assert not util.is_np_array()

    util.set_np()
    assert util.is_np_array() and util.is_np_shape()
    util.reset_np()
    assert not util.is_np_array()
    with pytest.raises(ValueError):
        util.set_np(shape=False, array=True)
    assert util.get_gpu_count() >= 0


def test_jit_function():
    from mxnet_tpu.jit import CachedOp, jit

    @jit
    def f(a, b):
        return a * 2 + b
    a = mx.nd.array([1.0, 2.0])
    b = mx.nd.array([3.0, 4.0])
    out = f(a, b)
    np.testing.assert_allclose(out.asnumpy(), [5.0, 8.0])

    op = CachedOp(lambda x: x + 1, static_shape=True)
    np.testing.assert_allclose(op(a).asnumpy(), [2.0, 3.0])
    with pytest.raises(ValueError):
        op(mx.nd.zeros((3, 3)))


def test_jit_symbol():
    from mxnet_tpu.jit import CachedOp
    x = mx.sym.Variable("x")
    y = mx.sym.Variable("y")
    z = 2 * x + y
    op = CachedOp(z)
    a = mx.nd.array([1.0, 2.0])
    b = mx.nd.array([10.0, 20.0])
    out = op(a, b)
    np.testing.assert_allclose(out.asnumpy(), [12.0, 24.0])


class TestPredictor:
    def _make(self):
        import numpy as onp
        from mxnet_tpu import symbol as sym
        from mxnet_tpu.predictor import Predictor
        data = sym.Variable("data")
        fc = sym.FullyConnected(data, num_hidden=3, name="fc")
        out = sym.softmax(fc, name="out")
        rng = onp.random.RandomState(0)
        args = {"fc_weight": mx.nd.array(rng.randn(3, 4).astype("float32")),
                "fc_bias": mx.nd.zeros((3,))}
        return Predictor(out.tojson(), input_shapes={"data": (2, 4)},
                         arg_params=args), args, rng

    def test_workflow_matches_eager(self):
        import numpy as onp
        p, args, rng = self._make()
        x = rng.randn(2, 4).astype("float32")
        p.set_input("data", x)
        p.forward()
        out = p.get_output(0)
        ref = mx.nd.softmax(mx.nd.FullyConnected(
            mx.nd.array(x), args["fc_weight"], args["fc_bias"],
            num_hidden=3)).asnumpy()
        onp.testing.assert_allclose(out, ref, atol=1e-5)
        assert p.get_output_shape(0) == (2, 3)

    def test_reshape_and_validation(self):
        import numpy as onp
        import pytest
        p, args, rng = self._make()
        with pytest.raises(KeyError):
            p.set_input("nope", onp.zeros((2, 4), "float32"))
        with pytest.raises(ValueError):
            p.set_input("data", onp.zeros((9, 4), "float32"))
        p.reshape({"data": (5, 4)})
        p.set_input("data", rng.randn(5, 4).astype("float32"))
        p.forward()
        assert p.get_output(0).shape == (5, 3)

    def test_from_checkpoint(self, tmp_path):
        import os
        import numpy as onp
        from mxnet_tpu import symbol as sym, io as mio
        from mxnet_tpu.predictor import Predictor
        rng = onp.random.RandomState(0)
        X = rng.randn(32, 4).astype("float32")
        y = (X.sum(1) > 0).astype("float32")
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        net = sym.SoftmaxOutput(sym.FullyConnected(data, num_hidden=2,
                                                   name="fc"), label,
                                name="softmax")
        it = mio.NDArrayIter(X, y, batch_size=16)
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.fit(it, num_epoch=1, optimizer_params={"learning_rate": 0.1})
        prefix = os.path.join(str(tmp_path), "m")
        mod.save_checkpoint(prefix, 1)
        pred = Predictor.from_checkpoint(prefix, 1,
                                         input_shapes={"data": (16, 4)})
        pred.set_input("data", X[:16])
        pred.forward()
        it.reset()
        ref = mod.predict(it).asnumpy()[:16]
        onp.testing.assert_allclose(pred.get_output(0), ref, atol=1e-5)


class TestTensorInspector:
    def test_check_and_dump(self, tmp_path, monkeypatch):
        import numpy as onp
        from mxnet_tpu.tensor_inspector import TensorInspector
        monkeypatch.chdir(tmp_path)
        a = mx.nd.array(onp.array([[1.0, onp.inf], [onp.nan, 4.0]]))
        ti = TensorInspector(a, tag="grads")
        assert ti.has_nan_or_inf()
        bad = ti.check_value()
        assert set(bad) == {(0, 1), (1, 0)}
        neg = TensorInspector(mx.nd.array(onp.array([-1.0, 2.0])))
        assert neg.check_value(lambda x: x < 0) == [(0,)]
        assert "2x2" in ti.print_string()
        f = ti.dump_to_file("g")
        assert f.endswith("_1.npy")
        loaded = onp.load(f)
        assert loaded.shape == (2, 2)
        assert ti.dump_to_file("g").endswith("_2.npy")


class TestReviewRegressions:
    def test_empty_dict_save_roundtrips_as_dict(self, tmp_path):
        f = str(tmp_path / "e.params")
        mx.nd.save(f, {})
        out = mx.nd.load(f)
        assert out == {}

    def test_get_output_shape_does_not_forward(self):
        import pytest
        from mxnet_tpu import symbol as sym
        from mxnet_tpu.predictor import Predictor
        import numpy as onp
        data = sym.Variable("data")
        out = sym.softmax(sym.FullyConnected(data, num_hidden=3, name="fc"))
        rng = onp.random.RandomState(0)
        p = Predictor(out.tojson(), input_shapes={"data": (2, 4)},
                      arg_params={"fc_weight": mx.nd.array(
                          rng.randn(3, 4).astype("float32")),
                          "fc_bias": mx.nd.zeros((3,))})
        assert p.get_output_shape(0) == (2, 3)
        with pytest.raises(RuntimeError):
            p.get_output(0)  # shape query must not have run forward

    def test_param_bytes_and_scalar_v3_write(self, tmp_path):
        import struct
        from mxnet_tpu.predictor import Predictor
        from mxnet_tpu import symbol as sym
        import numpy as onp
        # raw-bytes constructor path (MXPredCreate param_bytes)
        f = str(tmp_path / "p.params")
        w = mx.nd.array(onp.ones((3, 4), "float32"))
        mx.nd.save(f, {"arg:fc_weight": w, "arg:fc_bias": mx.nd.zeros((3,))})
        raw = open(f, "rb").read()
        data = sym.Variable("data")
        out = sym.FullyConnected(data, num_hidden=3, name="fc")
        p = Predictor(out.tojson(), param_raw_bytes=raw,
                      input_shapes={"data": (2, 4)})
        p.set_input("data", onp.ones((2, 4), "float32"))
        p.forward()
        assert onp.allclose(p.get_output(0), 4.0)
        # unnamed bytes rejected with a clear error
        f2 = str(tmp_path / "l.params")
        mx.nd.save(f2, [w])
        import pytest
        with pytest.raises(ValueError, match="NAMED"):
            Predictor(out.tojson(), param_raw_bytes=open(f2, "rb").read(),
                      input_shapes={"data": (2, 4)})
        # scalar records carry the V3 magic on disk
        f3 = str(tmp_path / "s.params")
        mx.nd.save(f3, [mx.nd.array(onp.float32(5.0).reshape(()))])
        with open(f3, "rb") as fh:
            fh.read(24)
            magic, = struct.unpack("<I", fh.read(4))
        assert magic == 0xF993faca


class TestNamingAndViz:
    def test_prefix_scope(self):
        from mxnet_tpu import symbol as sym
        from mxnet_tpu.name import Prefix
        with Prefix("net_"):
            fc = sym.FullyConnected(sym.Variable("data"), num_hidden=2)
        assert fc.name.startswith("net_")

    def test_attr_scope_propagates(self):
        from mxnet_tpu import symbol as sym
        from mxnet_tpu.attribute import AttrScope
        import pytest
        with AttrScope(ctx_group="dev1"):
            fc = sym.FullyConnected(sym.Variable("data"), num_hidden=2,
                                    name="fc")
            v = sym.Variable("w2")
        assert fc._outputs[0][0].attrs["ctx_group"] == "dev1"
        assert v._outputs[0][0].attrs["ctx_group"] == "dev1"
        with pytest.raises(ValueError):
            AttrScope(bad=1)

    def test_print_summary_and_plot(self):
        from mxnet_tpu import symbol as sym, visualization
        data = sym.Variable("data")
        net = sym.FullyConnected(
            sym.Activation(sym.FullyConnected(data, num_hidden=8,
                                              name="fc1"),
                           act_type="relu", name="a1"),
            num_hidden=2, name="fc2")
        out = visualization.print_summary(net, shape={"data": (4, 6)})
        assert "fc1" in out and "Total params: 74" in out
        dot = visualization.plot_network(net, shape={"data": (4, 6)})
        assert "fc1" in dot.source and "fc2" in dot.source

    def test_kvstore_server_shim(self):
        import mxnet_tpu as mx
        import pickle
        kv = mx.kv.create("local")
        from mxnet_tpu.kvstore_server import KVStoreServer
        srv = KVStoreServer(kv)
        ctrl = srv._controller()
        import mxnet_tpu.optimizer as opt
        ctrl(0, pickle.dumps(opt.create("sgd", learning_rate=0.5)))
        assert kv._optimizer.lr == 0.5
        assert srv.run() is None


class TestAttrScopeInference:
    def test_infer_shape_under_attr_scope(self):
        """Regression: scope attrs must never be fed to op kernels."""
        from mxnet_tpu import symbol as sym
        from mxnet_tpu.attribute import AttrScope
        with AttrScope(ctx_group="dev1"):
            fc = sym.FullyConnected(sym.Variable("data"), num_hidden=2,
                                    name="fc")
        arg_shapes, out_shapes, _ = fc.infer_shape(data=(4, 6))
        assert tuple(out_shapes[0]) == (4, 2)
        exe = fc.simple_bind(mx.cpu(), data=(4, 6))
        import numpy as onp
        exe.forward(is_train=False, data=onp.zeros((4, 6), "float32"))

    def test_explicit_attr_wins_over_scope(self):
        from mxnet_tpu import symbol as sym
        from mxnet_tpu.attribute import AttrScope
        with AttrScope(ctx_group="dev1"):
            fc = sym.FullyConnected(sym.Variable("data"), num_hidden=2,
                                    attr={"ctx_group": "dev9"}, name="f")
        assert fc._outputs[0][0].attrs["ctx_group"] == "dev9"

    def test_prefix_applies_to_explicit_names(self):
        from mxnet_tpu import symbol as sym
        from mxnet_tpu.name import Prefix
        with Prefix("net_"):
            fc = sym.FullyConnected(sym.Variable("data"), num_hidden=2,
                                    name="fc1")
        assert fc.name == "net_fc1"


def test_concurrent_eager_dispatch_thread_safety():
    """Concurrent eager op dispatch + autograd from multiple threads
    (ref strategy: tests/nightly/test_tlocal_racecondition.py,
    tests/python/unittest/test_thread_local.py — scopes and tapes are
    thread-local)."""
    import threading
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd

    errs = []

    def worker(seed):
        try:
            rs = onp.random.RandomState(seed)
            for _ in range(10):
                x = nd.array(rs.rand(8, 8).astype("float32"))
                x.attach_grad()
                with autograd.record():
                    y = (nd.dot(x, x) * 2.0).sum()
                y.backward()
                g = x.grad.asnumpy()
                assert onp.isfinite(g).all()
                # name scopes are thread-local too
                with mx.name.Prefix("t%d_" % seed):
                    s = mx.sym.var("v%d" % seed) * 2.0
                    # explicit VARIABLE names stay unprefixed (reference
                    # behavior); the OP node gets the thread's prefix
                    assert s.name.startswith("t%d_" % seed), s.name
                    assert s.list_arguments()[0] == "v%d" % seed
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs


def test_test_utils_symbolic_checks():
    """check_symbolic_forward/backward + same + set_default_context
    (VERDICT r4 weak #6: test_utils was a thin shim)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import test_utils as tu

    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    y = a * b + a
    av = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    bv = np.array([[2.0, 2.0], [2.0, 2.0]], np.float32)
    tu.check_symbolic_forward(y, [av, bv], [av * bv + av])
    og = np.ones_like(av)
    tu.check_symbolic_backward(y, [av, bv], [og],
                               [bv + 1.0, av])
    assert tu.same(np.array([1, 2]), mx.nd.array([1.0, 2.0]))
    assert not tu.same(np.array([1, 2]), np.array([1, 3]))
    assert len(tu.rand_shape_2d()) == 2 and len(tu.rand_shape_3d()) == 3

    prev = tu.default_context()
    try:
        tu.set_default_context(mx.cpu(1))
        assert tu.default_context() == mx.cpu(1)
    finally:
        tu.set_default_context(prev)


def test_check_consistency_defaults_to_device_vs_cpu():
    """Default ctx_list must include the current context when it is not
    plain cpu — a self-comparison no-op checks nothing (r4 weak #6)."""
    import mxnet_tpu as mx
    from mxnet_tpu import test_utils as tu

    seen = []

    def probe(x):
        seen.append(x.context)
        return x + 1

    with mx.Context("cpu", 1):
        tu.check_consistency(probe, [mx.nd.array([1.0, 2.0])])
    assert len(seen) == 2 and seen[0] != seen[1], seen
