"""Tests for the runtime control-surface modules: profiler, runtime
features, engine, storage, util, jit.

Mirrors coverage from the reference's tests/python/unittest/test_profiler.py,
test_runtime.py, test_engine.py (ref SURVEY.md §4).
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("CPU")
    assert feats.is_enabled("BF16")
    with pytest.raises(RuntimeError):
        feats.is_enabled("NO_SUCH_FEATURE")
    lst = mx.runtime.feature_list()
    assert any(f.name == "CPU" and f.enabled for f in lst)
    assert "CPU" in repr(feats)


def test_profiler_roundtrip(tmp_path):
    from mxnet_tpu import profiler
    fn = str(tmp_path / "profile.json")
    profiler.set_config(filename=fn, aggregate_stats=True)
    profiler.set_state("run")
    assert profiler.is_running()
    profiler.record_op("test_op", 123.0)
    d = profiler.Domain("unit")
    with d.new_task("work"):
        pass
    c = d.new_counter("ctr", 5)
    c += 2
    c -= 1
    d.new_marker("m").mark()
    ev = profiler.Event("ev")
    ev.start()
    ev.stop()
    profiler.pause()
    assert not profiler.is_running()
    profiler.resume()
    profiler.set_state("stop")
    profiler.dump()
    with open(fn) as f:
        trace = json.load(f)
    names = [e["name"] for e in trace["traceEvents"]]
    assert "test_op" in names
    assert "ctr" in names
    table = profiler.dumps()
    assert "test_op" in table


def test_profiler_bad_config():
    from mxnet_tpu import profiler
    with pytest.raises(ValueError):
        profiler.set_config(bogus_key=1)
    with pytest.raises(ValueError):
        profiler.set_state("bogus")


def test_engine_bulk_and_naive():
    from mxnet_tpu import engine
    assert engine.engine_type() == "ThreadedEnginePerDevice"
    prev = engine.set_bulk_size(30)
    assert engine.bulk_size() == 30
    with engine.bulk(5):
        assert engine.bulk_size() == 5
    assert engine.bulk_size() == 30
    engine.set_bulk_size(prev)

    os.environ["MXNET_ENGINE_TYPE"] = "NaiveEngine"
    try:
        assert engine.is_naive()
        a = mx.nd.array([1.0, 2.0])
        engine.maybe_sync(a._data)
    finally:
        del os.environ["MXNET_ENGINE_TYPE"]

    a = mx.nd.array([1.0, 2.0])
    engine.wait_for_var(a)
    engine.wait_for_all()
    assert engine.push_sync(lambda x: x + 1, 1) == 2


def test_storage_stats():
    from mxnet_tpu import storage
    a = mx.nd.zeros((64, 64))
    a.wait_to_read()
    st = storage.stats()
    assert len(st) >= 1
    assert all(s.bytes_in_use >= 0 for s in st)
    assert storage.total_bytes_in_use() >= 0
    storage.release_all()
    repr(st[0])


def test_util_scopes():
    from mxnet_tpu import util
    assert not util.is_np_shape()
    with util.np_shape(True):
        assert util.is_np_shape()
    assert not util.is_np_shape()

    @util.use_np
    def f():
        return util.is_np_array() and util.is_np_shape()
    assert f()
    assert not util.is_np_array()

    util.set_np()
    assert util.is_np_array() and util.is_np_shape()
    util.reset_np()
    assert not util.is_np_array()
    with pytest.raises(ValueError):
        util.set_np(shape=False, array=True)
    assert util.get_gpu_count() >= 0


def test_jit_function():
    from mxnet_tpu.jit import CachedOp, jit

    @jit
    def f(a, b):
        return a * 2 + b
    a = mx.nd.array([1.0, 2.0])
    b = mx.nd.array([3.0, 4.0])
    out = f(a, b)
    np.testing.assert_allclose(out.asnumpy(), [5.0, 8.0])

    op = CachedOp(lambda x: x + 1, static_shape=True)
    np.testing.assert_allclose(op(a).asnumpy(), [2.0, 3.0])
    with pytest.raises(ValueError):
        op(mx.nd.zeros((3, 3)))


def test_jit_symbol():
    from mxnet_tpu.jit import CachedOp
    x = mx.sym.Variable("x")
    y = mx.sym.Variable("y")
    z = 2 * x + y
    op = CachedOp(z)
    a = mx.nd.array([1.0, 2.0])
    b = mx.nd.array([10.0, 20.0])
    out = op(a, b)
    np.testing.assert_allclose(out.asnumpy(), [12.0, 24.0])
