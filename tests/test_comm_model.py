"""Communication-decomposition harness (benchmark/comm_model.py;
VERDICT r4 item 2 replaced the content-free one-core timeshare scaling
number with HLO-measured collective bytes + a validated projection)."""
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmark"))

import comm_model  # noqa: E402


def test_shape_bytes_handles_tuples_and_layouts():
    assert comm_model._shape_bytes("f32[512,128]{1,0}") == 512 * 128 * 4
    assert comm_model._shape_bytes("bf16[8]") == 16
    assert comm_model._shape_bytes(
        "(f32[128]{0}, s32[4,2]{1,0}, pred[])") == 512 + 32 + 1
    assert comm_model._shape_bytes("f32[]") == 4


def test_loop_aware_collective_accounting():
    """A collective inside a while body counts trip-count times — the
    exact bug the static count had (under-reported (L-1) layers)."""
    hlo = """\
HloModule m, is_scheduled=true

%cond (p: (s32[], f32[4])) -> pred[] {
  %p = (s32[], f32[4]{0}) parameter(0)
  %c = s32[] constant(3)
  %i = s32[] get-tuple-element(%p), index=0
  ROOT %lt = pred[] compare(%i, %c), direction=LT
}

%body (p: (s32[], f32[4])) -> (s32[], f32[4]) {
  %p = (s32[], f32[4]{0}) parameter(0)
  %g = f32[4]{0} get-tuple-element(%p), index=1
  %ar = f32[4]{0} all-reduce(%g), channel_id=1, to_apply=%add
  %i = s32[] get-tuple-element(%p), index=0
  ROOT %t = (s32[], f32[4]{0}) tuple(%i, %ar)
}

ENTRY %main (a: f32[8]) -> f32[8] {
  %a = f32[8]{0} parameter(0)
  %ar0 = f32[8]{0} all-reduce(%a), channel_id=2, to_apply=%add
  %t0 = (s32[], f32[4]{0}) tuple(%c0, %s)
  %w = (s32[], f32[4]{0}) while(%t0), condition=%cond, body=%body
  ROOT %r = f32[8]{0} copy(%ar0)
}
"""
    by_kind, counts, unresolved = comm_model.hlo_collective_bytes(hlo)
    # 8*4 at top level + 3 trips * 4*4 in the loop
    assert by_kind["all-reduce"] == 32 + 3 * 16
    assert counts["all-reduce"] == 1 + 3
    assert unresolved == 0


def test_async_start_collective_counts_result_only():
    """Regression: an async ``-start`` collective's HLO result is an
    (operand, result) tuple — the payload must be counted once, not
    doubled, and the matching ``-done`` line adds nothing."""
    hlo = """\
HloModule m, is_scheduled=true

ENTRY %main (a: f32[512,128]) -> f32[512,128] {
  %a = f32[512,128]{1,0} parameter(0)
  %ar-start = (f32[512,128]{1,0}, f32[512,128]{1,0}) all-reduce-start(%a), channel_id=1, to_apply=%add
  ROOT %ar-done = f32[512,128]{1,0} all-reduce-done(%ar-start)
}
"""
    by_kind, counts, unresolved = comm_model.hlo_collective_bytes(hlo)
    assert by_kind["all-reduce"] == 512 * 128 * 4  # once, not twice
    assert counts["all-reduce"] == 1
    assert unresolved == 0


def test_async_start_with_context_elements_counts_result_only():
    """collective-permute-start's result tuple carries two trailing
    u32[] context elements — the payload is still only the second
    element."""
    hlo = """\
HloModule m, is_scheduled=true

ENTRY %main (x: f32[1024]) -> f32[1024] {
  %x = f32[1024]{0} parameter(0)
  %cp-start = (f32[1024]{0}, f32[1024]{0}, u32[], u32[]) collective-permute-start(%x), channel_id=1
  ROOT %cp-done = f32[1024]{0} collective-permute-done(%cp-start)
}
"""
    by_kind, counts, unresolved = comm_model.hlo_collective_bytes(hlo)
    assert by_kind["collective-permute"] == 1024 * 4, by_kind
    assert counts["collective-permute"] == 1
    assert unresolved == 0


def test_tuple_elements_tracks_layout_braces():
    elems = comm_model._tuple_elements(
        "(f32[512,128]{1,0}, f32[512,128]{1,0})")
    assert elems == ["f32[512,128]{1,0}", " f32[512,128]{1,0}"]
    assert comm_model._tuple_elements("f32[8]{0}") == []


def test_pure_dp_measurement_matches_analytic_model():
    """End-to-end on the virtual mesh: the HLO-measured all-reduce
    payload of the pure-dp train step must match the analytic model —
    the trust gate the SCALING_r05 projection rests on. With the
    auto-selected ce_local_accum (dp>1, chunked CE) the unembedding
    grad accumulates locally and reduces ONCE inside the param
    all-reduce, so the wire is exactly params + the scalar loss."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    V, D = 512, 128
    m = comm_model.measure_config(
        "pure_dp", {"dp": 8},
        dict(vocab_size=V, dim=D, n_layers=2, n_heads=4,
             ffn_hidden=4 * D, attn_mode="local", loss_chunks=4),
        B=16, S=64)
    assert m["unresolved_loops"] == 0
    analytic = 4 * (m["params"] + 1)
    got = m["collective_payload_bytes"]["all-reduce"]
    assert abs(got - analytic) / analytic < 0.05, (got, analytic)
    # pure dp must not need any other collective kind
    assert m["collective_payload_bytes"]["collective-permute"] == 0
    assert m["collective_payload_bytes"]["all-to-all"] == 0


def test_pure_dp_per_chunk_reduction_when_local_accum_off():
    """The pre-local-accum wire, pinned: with ``ce_local_accum=False``
    the scan-carried unembedding grad all-reduces once per chunk —
    (chunks-1)*vocab*dim extra payload (the first reduction merges into
    the param all-reduce). The delta between this test and the one
    above IS the single-reduction saving."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    V, D = 512, 128
    m = comm_model.measure_config(
        "pure_dp_chunk_ar", {"dp": 8},
        dict(vocab_size=V, dim=D, n_layers=2, n_heads=4,
             ffn_hidden=4 * D, attn_mode="local", loss_chunks=4,
             ce_local_accum=False),
        B=16, S=64)
    assert m["unresolved_loops"] == 0
    analytic = 4 * (m["params"] + 3 * V * D + 1)
    got = m["collective_payload_bytes"]["all-reduce"]
    assert abs(got - analytic) / analytic < 0.05, (got, analytic)


def test_gspmd_keeps_scan_accumulated_reduction_in_loop():
    """Minimal reproduction of the chunked-CE finding: a scan that
    accumulates a batch-sharded contraction gets its all-reduce INSIDE
    the loop (once per iteration), because scan carries must hold a
    concrete sharding. This pins the structural behavior the
    SCALING_r05 'observed' projection models; if a jax upgrade starts
    hoisting it, this test fails and the projection should be updated
    to the ideal pattern."""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = Mesh(onp.array(jax.devices()[:8]).reshape(8), ("dp",))
    xs = jnp.zeros((4, 16, 8))
    ys = jnp.zeros((4, 16, 32))

    def f(xs, ys):
        def body(acc, args):
            x, y = args
            return acc + jnp.einsum("bd,bv->dv", x, y), 0.0
        return lax.scan(body, jnp.zeros((8, 32)), (xs, ys))[0]

    sh = NamedSharding(mesh, P(None, "dp"))
    txt = jax.jit(f, in_shardings=(sh, sh)).lower(xs, ys) \
        .compile().as_text()
    by, counts, unresolved = comm_model.hlo_collective_bytes(txt)
    assert unresolved == 0
    # in-loop: 4 dynamic executions of the [8, 32] f32 reduction
    assert counts["all-reduce"] == 4, counts
    assert by["all-reduce"] == 4 * 8 * 32 * 4, by


def test_peak_tflops_table_dtype_aware():
    """ISSUE 17 satellite: the dtype-aware peak table — f32 is half
    the bf16 MXU rate, int8 double (the PR 9 quantized-matmul path),
    f16 rides the bf16 MXU number, unknown dtypes fall back to bf16.
    The legacy scalar stays aliased for old callers."""
    t = comm_model.ASSUMPTIONS["peak_tflops"]
    assert comm_model.peak_tflops("bf16") == t["bf16"] == 197.0
    assert comm_model.peak_tflops("f32") == t["f32"] == 98.5
    assert comm_model.peak_tflops("int8") == t["int8"] == 394.0
    assert comm_model.peak_tflops("f16") == t["bf16"]
    assert comm_model.peak_tflops("float8_e4m3") == t["bf16"]
    assert comm_model.peak_tflops() == t["bf16"]
    assert comm_model.ASSUMPTIONS["bf16_peak_tflops"] == t["bf16"]
