"""Round-4 contrib gap closures (VERDICT r3 items 5-6):
gluon.contrib.cnn.DeformableConvolution, gluon.contrib.data
(WikiText2/IntervalSampler), and the mx.contrib.{autograd,io,ndarray,
symbol} shims."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon


def test_deformable_convolution_block():
    net = gluon.contrib.cnn.DeformableConvolution(
        8, kernel_size=3, padding=1, in_channels=0, activation="relu")
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 4, 9, 9).astype("f"))
    y = net(x)
    assert y.shape == (2, 8, 9, 9)
    # offset weights init to zeros -> equals a plain conv at start
    # (the v1 paper's init); relu keeps it >= 0
    assert float(y.asnumpy().min()) >= 0.0
    params = net.collect_params()
    assert any("offset_weight" in k for k in params)
    assert any("deformable_conv_weight" in k for k in params)


def test_deformable_convolution_trains():
    from mxnet_tpu import autograd
    net = gluon.contrib.cnn.DeformableConvolution(4, kernel_size=3,
                                                  padding=1)
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 3, 7, 7).astype("f"))
    with autograd.record():
        y = net(x)
        loss = (y * y).sum()
    loss.backward()
    g = net.collect_params()
    grads = [p.grad() for p in g.values() if p.grad_req != "null"]
    assert any(float((gr * gr).sum().asnumpy()) > 0 for gr in grads)


def test_interval_sampler():
    s = gluon.contrib.data.IntervalSampler(13, interval=3)
    assert list(s) == [0, 3, 6, 9, 12, 1, 4, 7, 10, 2, 5, 8, 11]
    s = gluon.contrib.data.IntervalSampler(13, interval=3, rollover=False)
    assert list(s) == [0, 3, 6, 9, 12]
    assert len(s) == 13


def test_wikitext2(tmp_path, monkeypatch):
    # explicit local tokens file (the reference's downloaded layout)
    root = tmp_path / "wikitext-2"
    root.mkdir()
    (root / "wiki.train.tokens").write_text(
        "the cat sat on the mat\nthe dog ran fast\n" * 30)
    ds = gluon.contrib.data.WikiText2(root=str(root), segment="train",
                                      seq_len=5)
    assert len(ds) > 0
    data, label = ds[0]
    assert data.shape == (5,) and label.shape == (5,)
    # next-token labels: label[i] == data[i+1] within the flat stream
    d0 = ds._data.asnumpy().ravel()
    l0 = ds._label.asnumpy().ravel()
    np.testing.assert_array_equal(d0[1:], l0[:-1])
    assert ds.vocabulary is not None
    # synthetic fallback path (zero-egress CI)
    monkeypatch.setenv("MXTPU_SYNTHETIC_DATA", "1")
    ds2 = gluon.contrib.data.WikiText2(root=str(tmp_path / "nope"),
                                       segment="test", seq_len=7)
    assert len(ds2) > 0 and ds2[0][0].shape == (7,)


def test_contrib_autograd_shim():
    from mxnet_tpu.contrib import autograd as ag

    def loss_fn(x):
        return (x * x).sum()

    g_fn = ag.grad_and_loss(loss_fn)
    x = mx.nd.array(np.array([1.0, 2.0, 3.0], "f"))
    grads, loss = g_fn(x)
    np.testing.assert_allclose(grads[0].asnumpy(), [2, 4, 6], rtol=1e-6)
    np.testing.assert_allclose(float(loss.asnumpy()), 14.0, rtol=1e-6)
    only = ag.grad(loss_fn)
    np.testing.assert_allclose(only(x)[0].asnumpy(), [2, 4, 6], rtol=1e-6)


def test_contrib_dataloader_iter():
    from mxnet_tpu.contrib.io import DataLoaderIter
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    n = 10
    ds = ArrayDataset(np.arange(n * 4, dtype="f").reshape(n, 4),
                      np.arange(n, dtype="f"))
    it = DataLoaderIter(DataLoader(ds, batch_size=4))
    batches = list(it)
    assert len(batches) == 3
    # last batch zero-padded to full batch size with pad set
    assert batches[-1].data[0].shape == (4, 4)
    it.reset()
    assert len(list(it)) == 3


def test_contrib_namespace_shims():
    from mxnet_tpu.contrib import ndarray as cnd
    from mxnet_tpu.contrib import symbol as csym
    assert hasattr(cnd, "box_nms") or hasattr(cnd, "MultiBoxPrior")
    assert hasattr(csym, "cond") or hasattr(csym, "while_loop")
