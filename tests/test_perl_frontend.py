"""Perl frontend CI (VERDICT r4 item 4: a second generated non-C++
language frontend over the C ABI).

Builds perl-package/ (XS over the MXT* entry points, plus
AI::MXTpu::Ops generated from the live registry by gen_op_pm.py) and
runs examples/train_mnist.pl — which must train the same MLP to the
same loss-drops-5x criterion as example/capi/train_mnist.c.

Ref slot: perl-package/ (AI::MXNetCAPI SWIG wrapper + AI::MXNet),
40.6k LoC in the reference; here ~450 handwritten lines + ~1.2k
generated because dispatch/autograd/XLA live behind the shared ABI.
"""
import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "perl-package")
LIB = os.path.join(REPO, "mxnet_tpu", "libmxnet_tpu.so")

pytestmark = pytest.mark.skipif(
    shutil.which("perl") is None or not os.path.exists(LIB),
    reason="perl or libmxnet_tpu.so unavailable")


def _env():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    return env


@pytest.fixture(scope="module")
def built_pkg():
    if not os.path.exists(os.path.join(PKG, "blib", "arch", "auto", "AI",
                                       "MXTpu", "MXTpu.so")):
        subprocess.run(["perl", "Makefile.PL"], cwd=PKG, check=True,
                       capture_output=True, timeout=120)
        subprocess.run(["make"], cwd=PKG, check=True, capture_output=True,
                       timeout=300)
    return PKG


def test_ops_pm_is_current(built_pkg, tmp_path):
    """The checked-in generated Ops.pm must match the live registry
    (same regeneration contract as cpp-package op.h). Generates to a
    temp path and compares contents — the working tree is never
    mutated, and the check doesn't depend on `git diff` (which would
    pass vacuously on a dirty or non-git checkout)."""
    fresh = tmp_path / "Ops.pm"
    gen = subprocess.run(
        ["python", os.path.join(PKG, "scripts", "gen_op_pm.py"),
         str(fresh)],
        env=_env(), capture_output=True, text=True, timeout=300)
    assert gen.returncode == 0, gen.stderr
    checked_in = os.path.join(PKG, "lib", "AI", "MXTpu", "Ops.pm")
    with open(checked_in) as f:
        want = f.read()
    got = fresh.read_text()
    assert got == want, \
        "generated Ops.pm is stale — rerun gen_op_pm.py"


def test_ndarray_roundtrip_and_ops(built_pkg):
    r = subprocess.run(
        ["perl", "-Mblib", "-MAI::MXTpu", "-MAI::MXTpu::Ops", "-e", """
my $x = AI::MXTpu::NDArray->new([2, 3], [-1, 2, -3, 4, -5, 6]);
my $r = AI::MXTpu::Ops::relu($x);
die 'relu' unless "@{$r->aslist}" eq '0 2 0 4 0 6';
die 'shape' unless "@{$r->shape}" eq '2 3';
my $s = AI::MXTpu::Ops::sum_($x);
die 'sum' unless abs($s->asscalar - 3) < 1e-6;
my $fc = AI::MXTpu::Ops::FullyConnected(
    $x, AI::MXTpu::NDArray->new([4, 3], [(0.5) x 12]),
    AI::MXTpu::NDArray->zeros([4]), num_hidden => 4);
die 'fc shape' unless "@{$fc->shape}" eq '2 4';
print "PERL-OPS-OK\\n";
"""],
        cwd=PKG, env=_env(), capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PERL-OPS-OK" in r.stdout


def test_perl_trains_mnist(built_pkg):
    """The headline: a Perl training loop over the generated op surface
    reaches the same convergence bar as the C demo."""
    r = subprocess.run(
        ["perl", "-Mblib", os.path.join("examples", "train_mnist.pl")],
        cwd=PKG, env=_env(), capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Perl-frontend MNIST training OK" in r.stdout
