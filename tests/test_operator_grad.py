"""Finite-difference gradient checks across the op surface — the
reference's core operator test strategy (ref: tests/python/unittest/
test_operator.py's pervasive check_numeric_gradient usage,
python/mxnet/test_utils.py:883)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_numeric_gradient


def _r(*shape, seed=0, scale=1.0, shift=0.0):
    rng = onp.random.RandomState(seed)
    return (rng.rand(*shape).astype("float32") * scale + shift)


class TestElementwiseGrads:
    @pytest.mark.parametrize("op,domain", [
        ("exp", (0.1, 1.0)), ("log", (0.5, 2.0)), ("sqrt", (0.5, 2.0)),
        ("tanh", (-1.0, 1.0)), ("sigmoid", (-2.0, 2.0)),
        ("erf", (-1.0, 1.0)), ("rsqrt", (0.5, 2.0)),
        ("expm1", (-0.5, 0.5)), ("log1p", (0.1, 1.0)),
        ("arctan", (-1.0, 1.0)), ("sinh", (-1.0, 1.0)),
    ])
    def test_unary(self, op, domain):
        lo, hi = domain
        x = _r(3, 4, scale=hi - lo, shift=lo)
        fn = getattr(nd, op)
        check_numeric_gradient(lambda a: fn(a).sum(), [x])

    def test_binary_broadcast(self):
        a = _r(3, 4, seed=1, shift=0.5)
        b = _r(1, 4, seed=2, shift=0.5)
        check_numeric_gradient(
            lambda x, y: (x * y + x / y).sum(), [a, b])

    def test_power(self):
        a = _r(3, 3, shift=0.5)
        check_numeric_gradient(lambda x: (x ** 2.5).sum(), [a])

    def test_clip_where(self):
        a = _r(3, 4, scale=2.0, shift=-1.0)
        check_numeric_gradient(
            lambda x: nd.clip(x, -0.4, 0.4).sum(), [a])


class TestNNGrads:
    def test_fully_connected(self):
        x, w, b = _r(4, 5), _r(3, 5, seed=1), _r(3, seed=2)
        check_numeric_gradient(
            lambda a, ww, bb: nd.FullyConnected(
                a, ww, bb, num_hidden=3).sum(), [x, w, b])

    def test_convolution(self):
        x = _r(2, 3, 6, 6)
        w = _r(4, 3, 3, 3, seed=1, scale=0.5)
        check_numeric_gradient(
            lambda a, ww: (nd.Convolution(
                a, ww, None, kernel=(3, 3), num_filter=4, no_bias=True,
                pad=(1, 1)) ** 2).sum(), [x, w], rtol=2e-2)

    def test_pooling(self):
        x = _r(2, 2, 6, 6)
        check_numeric_gradient(
            lambda a: (nd.Pooling(a, kernel=(2, 2), stride=(2, 2),
                                  pool_type="avg") ** 2).sum(), [x])

    def test_softmax_ce_path(self):
        x = _r(4, 5, scale=2.0, shift=-1.0)
        check_numeric_gradient(
            lambda a: (nd.log_softmax(a)[:, 0]).sum(), [x])

    def test_layer_norm(self):
        x = _r(4, 6, scale=2.0)
        g, b = _r(6, seed=1), _r(6, seed=2)
        check_numeric_gradient(
            lambda a, gg, bb: (nd.LayerNorm(a, gg, bb) ** 2).sum(),
            [x, g, b], rtol=2e-2)

    def test_batchnorm_inference_grad(self):
        x = _r(4, 3, 2, 2)
        g, b = _r(3, seed=1, shift=0.5), _r(3, seed=2)
        mean, var = _r(3, seed=3), _r(3, seed=4, shift=0.5)
        def bn(a):
            out = nd.BatchNorm(a, nd.array(g), nd.array(b), nd.array(mean),
                               nd.array(var), use_global_stats=True)
            out = out[0] if isinstance(out, tuple) else out
            return (out ** 2).sum()
        check_numeric_gradient(bn, [x], rtol=2e-2)

    def test_activation_leaky(self):
        x = _r(3, 4, scale=2.0, shift=-1.0)
        check_numeric_gradient(
            lambda a: nd.LeakyReLU(a, slope=0.3).sum(), [x])


class TestLinalgGrads:
    def test_dot(self):
        a, b = _r(3, 4), _r(4, 2, seed=1)
        check_numeric_gradient(lambda x, y: (nd.dot(x, y) ** 2).sum(),
                               [a, b])

    def test_batch_dot(self):
        a, b = _r(2, 3, 4), _r(2, 4, 2, seed=1)
        check_numeric_gradient(
            lambda x, y: (nd.batch_dot(x, y) ** 2).sum(), [a, b])

    def test_norm(self):
        a = _r(3, 4, shift=0.5)
        check_numeric_gradient(lambda x: nd.norm(x), [a])


class TestShapeGrads:
    def test_reshape_transpose_concat(self):
        a = _r(2, 6)
        b = _r(2, 6, seed=1)
        check_numeric_gradient(
            lambda x, y: (nd.concat(nd.transpose(x.reshape((3, 4))),
                                    nd.transpose(y.reshape((3, 4))),
                          dim=0) ** 2).sum(), [a, b])

    def test_slice_take(self):
        a = _r(5, 4)
        idx = nd.array(onp.array([3, 1], "int32"))
        check_numeric_gradient(
            lambda x: (nd.take(x, idx, axis=0) ** 2).sum(), [a])

    def test_sequence_mask(self):
        a = _r(4, 3, 2)  # (T, N, C)
        lens = nd.array(onp.array([2, 4, 1], "int32"))
        check_numeric_gradient(
            lambda x: (nd.SequenceMask(
                x, sequence_length=lens, use_sequence_length=True)
                ** 2).sum(), [a])


class TestReduceGrads:
    @pytest.mark.parametrize("op", ["sum", "mean", "max", "min", "prod"])
    def test_reduce(self, op):
        a = _r(3, 4, shift=0.5, seed=7)
        fn = getattr(nd, op)
        check_numeric_gradient(lambda x: (fn(x, axis=1) ** 2).sum(), [a])
