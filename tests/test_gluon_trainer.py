"""Gluon Trainer + KVStore tests
(ref: tests/python/unittest/test_gluon_trainer.py, test_kvstore.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def _make_net():
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize(mx.init.Uniform(0.1))
    return net


def test_trainer_step_reduces_loss():
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.uniform(-1, 1, (32, 4)))
    true_w = rng.uniform(-1, 1, (4, 1)).astype("float32")
    y = mx.nd.array(rng.uniform(-1, 1, (32, 4)).dot(true_w))
    # use same x for y computation
    y = mx.nd.array(x.asnumpy().dot(true_w))

    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.L2Loss()

    losses = []
    for _ in range(20):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(batch_size=32)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0] * 0.3, losses


def test_trainer_lr_access_and_set():
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.25})
    assert trainer.learning_rate == 0.25
    trainer.set_learning_rate(0.1)
    assert trainer.learning_rate == 0.1


def test_trainer_save_load_states(tmp_path):
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    x = mx.nd.ones((8, 4))
    y = mx.nd.ones((8, 1))
    loss_fn = gluon.loss.L2Loss()
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(8)
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)
    assert os.path.exists(fname)

    net2 = _make_net()
    trainer2 = gluon.Trainer(net2.collect_params(), "adam",
                             {"learning_rate": 0.1})
    with autograd.record():
        loss = loss_fn(net2(x), y)
    loss.backward()
    trainer2.step(8)
    trainer2.load_states(fname)
    assert trainer2._optimizer.num_update == trainer._optimizer.num_update


def test_trainer_allreduce_then_update():
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.nd.ones((4, 4))
    y = mx.nd.ones((4, 1))
    loss_fn = gluon.loss.L2Loss()
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.allreduce_grads()
    trainer.update(4)


def test_trainer_single_updater_reality():
    """The dead multi-updater list is gone: ONE updater owns all state
    (a Parameter is one logical mesh-placed array here), which is also
    the single well-defined update list the fused step traces."""
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    assert not hasattr(trainer, "_updaters")
    assert trainer._updater.optimizer is trainer._optimizer


def test_failed_update_leaves_grads_fresh():
    """Stale-grad regression: _update must age grads only AFTER the
    update path actually ran — a raising updater leaves them fresh so a
    retried step works instead of tripping the stale-grad check."""
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.nd.ones((4, 4))
    y = mx.nd.ones((4, 1))
    loss_fn = gluon.loss.L2Loss()
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()

    real_updater = trainer._updater

    class _Flaky:
        def __init__(self):
            self.fail = True

        def __call__(self, i, g, w):
            if self.fail:
                raise RuntimeError("simulated optimizer failure")
            return real_updater(i, g, w)

    flaky = _Flaky()
    trainer._updater = flaky
    with pytest.raises(RuntimeError, match="simulated"):
        trainer.step(4)
    # grads still look fresh: the update never happened
    for p in net.collect_params().values():
        assert p.data()._fresh_grad is True
    # retry WITHOUT a new backward must neither warn stale nor skip
    flaky.fail = False
    before = {n: p.data().asnumpy().copy()
              for n, p in net.collect_params().items()}
    trainer.step(4)
    for n, p in net.collect_params().items():
        assert not np.array_equal(before[n], p.data().asnumpy())
        assert p.data()._fresh_grad is False


def test_update_on_kvstore_failed_pushpull_keeps_grads_fresh():
    """Under update_on_kvstore the pushpull IS the update: when it
    raises, step() aborts before any bookkeeping, so params still look
    fresh; once it succeeds the flag clears."""
    class _FlakyKV:
        fail = True

        def set_optimizer(self, o):
            pass

        def init(self, k, v):
            pass

        def pushpull(self, k, grad, out=None, priority=0):
            if self.fail:
                raise RuntimeError("wire down")
            out -= grad * 0.0  # applied-update stand-in

    net = _make_net()
    kv = _FlakyKV()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv,
                            update_on_kvstore=True)
    x = mx.nd.ones((4, 4))
    y = mx.nd.ones((4, 1))
    loss_fn = gluon.loss.L2Loss()
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    with pytest.raises(RuntimeError, match="wire down"):
        trainer.step(4)
    for p in net.collect_params().values():
        assert p.data()._fresh_grad is True
    kv.fail = False
    trainer.step(4)
    for p in net.collect_params().values():
        assert p.data()._fresh_grad is False


# -- kvstore ----------------------------------------------------------------

def test_kvstore_push_pull_single():
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.ones((2, 3)))
    out = mx.nd.zeros((2, 3))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones((2, 3)))
    kv.push(3, mx.nd.ones((2, 3)) * 4)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 4.0))


def test_kvstore_aggregation():
    kv = mx.kv.create("device")
    kv.init("w", mx.nd.zeros((2,)))
    vals = [mx.nd.ones((2,)), mx.nd.ones((2,)) * 2, mx.nd.ones((2,)) * 3]
    kv.push("w", vals)
    out = mx.nd.zeros((2,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2,), 6.0))


def test_kvstore_list_keys():
    kv = mx.kv.create()
    keys = [5, 7, 9]
    kv.init(keys, [mx.nd.ones((2,))] * 3)
    outs = [mx.nd.zeros((2,)) for _ in keys]
    kv.pull(keys, out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), np.ones((2,)))


def test_kvstore_updater():
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.ones((2,)))

    def updater(key, grad, weight):
        weight += grad * 2
    kv.set_updater(updater)
    kv.push(0, mx.nd.ones((2,)))
    out = mx.nd.zeros((2,))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2,), 3.0))


def test_kvstore_set_optimizer_server_side():
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.ones((4,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
    kv.push(0, mx.nd.ones((4,)))
    out = mx.nd.zeros((4,))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((4,), 0.5))


def test_kvstore_pushpull_and_broadcast():
    kv = mx.kv.create("tpu")
    out = mx.nd.zeros((3,))
    kv.broadcast("b", mx.nd.ones((3,)), out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones((3,)))
    res = mx.nd.zeros((3,))
    kv.pushpull("b", mx.nd.ones((3,)) * 2, out=res)
    np.testing.assert_allclose(res.asnumpy(), np.full((3,), 2.0))


def test_kvstore_invalid_type():
    with pytest.raises(ValueError):
        mx.kv.create("bogus")


def test_clip_global_norm():
    arrays = [mx.nd.ones((2, 2)) * 3, mx.nd.ones((2,)) * 4]
    total = gluon.utils.clip_global_norm(arrays, 1.0)
    norm = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert norm <= 1.0 + 1e-5
    assert total > 1.0


def test_split_and_load():
    data = mx.nd.arange(8).reshape((4, 2))
    slices = gluon.utils.split_data(data, 2)
    assert len(slices) == 2 and slices[0].shape == (2, 2)
    with pytest.raises(ValueError):
        gluon.utils.split_data(data, 3)
    loaded = gluon.utils.split_and_load(data.asnumpy(), [mx.cpu()])
    assert loaded[0].shape == (4, 2)
