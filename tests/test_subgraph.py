"""Subgraph partitioning seam (VERDICT r2 item 10).

ref: src/operator/subgraph/subgraph_property.h SubgraphProperty +
build_subgraph.cc — select nodes by predicate, replace with a fused
node backed by a user compile function; the fused graph must still
train.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.symbol.subgraph import SubgraphProperty, partition


class ConvBNRelu(SubgraphProperty):
    name = "convbnrelu"

    def select(self, node):
        return node.op in ("Convolution", "BatchNorm", "Activation")


def _net():
    data = mx.sym.var("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1),
                           no_bias=True, name="c1")
    b = mx.sym.BatchNorm(c, fix_gamma=False, name="bn1")
    r = mx.sym.Activation(b, act_type="relu", name="r1")
    f = mx.sym.Flatten(r)
    fc = mx.sym.FullyConnected(f, num_hidden=3, name="fc")
    return mx.sym.LinearRegressionOutput(fc, mx.sym.var("label"),
                                         name="out")


def _op_names(sym):
    return [n.op for n in sym._topo() if not n.is_variable()]


class TestPartition:
    def test_conv_bn_relu_fuses_to_one_node(self):
        sym = _net()
        fused = partition(sym, ConvBNRelu())
        ops = _op_names(fused)
        assert not any(o in ("Convolution", "BatchNorm", "Activation")
                       for o in ops), ops
        assert sum(o.startswith("_subgraph_convbnrelu") for o in ops) == 1
        # the rest of the graph is untouched
        assert "FullyConnected" in ops and "flatten" in ops
        # arguments survive (conv weight, bn params)
        assert set(sym.list_arguments()) == set(fused.list_arguments())

    def test_fused_numerics_match_unfused(self):
        sym = _net()
        fused = partition(sym, ConvBNRelu())
        shapes = {"data": (2, 3, 8, 8), "label": (2, 3)}
        ex_a = sym.simple_bind(grad_req="null", **shapes)
        ex_b = fused.simple_bind(grad_req="null", **shapes)
        rng = np.random.RandomState(0)
        for name, arr in ex_a.arg_dict.items():
            v = rng.rand(*arr.shape).astype("float32")
            arr._data = mx.nd.array(v)._data
            ex_b.arg_dict[name]._data = mx.nd.array(v)._data
        (ya,) = ex_a.forward()
        (yb,) = ex_b.forward()
        np.testing.assert_allclose(ya.asnumpy(), yb.asnumpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_fused_graph_trains(self):
        """The done-criterion: the partitioned conv+bn+relu graph
        TRAINS — gradients flow through the fused node."""
        fused = partition(_net(), ConvBNRelu())
        ex = fused.simple_bind(grad_req="write",
                               data=(4, 3, 8, 8), label=(4, 3))
        rng = np.random.RandomState(1)
        for name, arr in ex.arg_dict.items():
            if name in ("data", "label"):
                continue
            if name.endswith("gamma"):
                arr._data = mx.nd.ones(arr.shape)._data
            elif not name.endswith(("beta", "bias")):
                arr._data = mx.nd.array(
                    rng.normal(0, 0.3, arr.shape).astype("float32"))._data
        x = rng.rand(4, 3, 8, 8).astype("float32")
        y = rng.rand(4, 3).astype("float32")
        ex.arg_dict["data"]._data = mx.nd.array(x)._data
        ex.arg_dict["label"]._data = mx.nd.array(y)._data
        losses = []
        for _ in range(25):
            (pred,) = ex.forward(is_train=True)
            losses.append(float(((pred.asnumpy() - y) ** 2).mean()))
            ex.backward()
            for name, g in ex.grad_dict.items():
                if g is None or name in ("data", "label"):
                    continue
                w = ex.arg_dict[name]
                w._data = w._data - 0.05 * g._data
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    def test_user_compile_fn_is_used(self):
        """The seam's point: the property hands the region to a CUSTOM
        compiler."""
        calls = {}

        class Jitted(ConvBNRelu):
            name = "jitted"

            def compile(self, subgraph, input_names):
                calls["subgraph_ops"] = [n.op for n in subgraph._topo()
                                         if not n.is_variable()]
                calls["inputs"] = list(input_names)
                import jax
                inner = super().compile(subgraph, input_names)
                return jax.jit(inner, static_argnames=("_training",))

        fused = partition(_net(), Jitted())
        assert calls["subgraph_ops"] == ["Convolution", "BatchNorm",
                                        "Activation"]
        assert len(calls["inputs"]) == 6  # data + conv w + 4 bn params
        ex = fused.simple_bind(grad_req="null",
                               data=(1, 3, 8, 8), label=(1, 3))
        (out,) = ex.forward()
        assert out.shape == (1, 3)

    def test_select_input_veto_stops_growth(self):
        class OnlyRelu(ConvBNRelu):
            name = "onlyrelu"

            def select_input(self, node, producer):
                return False  # never grow: each region is a single node

        fused = partition(_net(), OnlyRelu())
        ops = _op_names(fused)
        # three single-node regions instead of one chain
        assert sum(o.startswith("_subgraph_onlyrelu") for o in ops) == 3

    def test_no_match_returns_same_symbol(self):
        class Nothing(SubgraphProperty):
            def select(self, node):
                return False

        sym = _net()
        assert partition(sym, Nothing()) is sym


class TestRobustness:
    def test_deepcopy_round_trip_still_binds(self):
        """_cf_cache is not serialized; inference must rebuild the
        inner graph from the __fused_json__ attr."""
        import copy
        fused = copy.deepcopy(partition(_net(), ConvBNRelu()))
        ex = fused.simple_bind(grad_req="null",
                               data=(1, 3, 8, 8), label=(1, 3))
        (out,) = ex.forward()
        assert out.shape == (1, 3)

    def test_head_inside_chain_not_duplicated(self):
        """A chain member that is also a graph output must stay
        un-swallowed (no duplicate unfused copy)."""
        data = mx.sym.var("data")
        c = mx.sym.Convolution(data, kernel=(1, 1), num_filter=2,
                               no_bias=True, name="c")
        b = mx.sym.BatchNorm(c, name="b")
        r = mx.sym.Activation(b, act_type="relu", name="r")
        g = mx.sym.Group([b, r])
        fused = partition(g, ConvBNRelu())
        ops = _op_names(fused)
        # bn feeds a head: conv+bn stay out (or form their own region
        # ending at the head) — no op may appear twice
        assert len(ops) == len(set(ops)), ops
        ex = fused.simple_bind(grad_req="null", data=(1, 3, 4, 4))
        o1, o2 = ex.forward()
        np.testing.assert_allclose(np.maximum(o1.asnumpy(), 0),
                                   o2.asnumpy(), rtol=1e-6)

    def test_control_flow_infer_after_forward(self):
        """The fusion branch in infer_shape must not trip over
        control-flow nodes (which use _cf_cache for their programs)."""
        data = mx.sym.var("data")
        out = mx.sym.contrib.foreach(
            lambda x, s: (x + s, s), data, mx.sym.var("init"))[0] \
            if hasattr(mx.sym.contrib, "foreach") else None
        if out is None:
            pytest.skip("no foreach")
        ex = out.simple_bind(grad_req="null", data=(3, 2), init=(2,))
        ex.forward()
        shapes = out.infer_shape(data=(3, 2), init=(2,))
        assert shapes is not None
