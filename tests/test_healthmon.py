"""Training-health plane tests (ISSUE 15): in-graph sentinels, anomaly
actions (record / skip_step / halt), the numerics flight-record dump,
Monitor routing through the fused step's health outputs, cross-rank SDC
divergence gauges, TensorInspector device paths, and the AMP
loss-scaler accounting fold.

The acceptance pair the issue pins:

- chaos: injected gradient corruption (``health.grad.corrupt``) is
  detected within one step, trips exactly ONE ``numerics`` dump naming
  the offending bucket/params (and the suspect rank), and a
  ``skip_step`` run's final params are bitwise-equal to a run where the
  poisoned step never happened;
- fault-free twin: zero anomalies, and ``MXTPU_HEALTH=1`` training is
  bitwise-identical to ``MXTPU_HEALTH=0`` — observability must not
  perturb the numerics it observes.
"""
import json
import logging
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu import profiler
from mxnet_tpu.gluon import nn
from mxnet_tpu._debug import faultpoint
from mxnet_tpu._debug import flightrec
from mxnet_tpu._debug import goodput
from mxnet_tpu._debug import healthmon
from mxnet_tpu._debug import watchdog
from mxnet_tpu.monitor import Monitor
from mxnet_tpu.tensor_inspector import TensorInspector


@pytest.fixture(autouse=True)
def _isolated(monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_FLIGHTREC_DIR", str(tmp_path / "frec"))
    monkeypatch.delenv("MXTPU_HEALTH", raising=False)
    monkeypatch.delenv("MXTPU_HEALTH_ACTION", raising=False)
    faultpoint.reset()
    healthmon.reset()
    flightrec.reset_ring()
    yield
    faultpoint.reset()
    healthmon.reset()


def _batches(n, batch=8, in_dim=8, out_dim=4, seed=0):
    rs = np.random.RandomState(seed)
    return [(rs.rand(batch, in_dim).astype("float32"),
             rs.rand(batch, out_dim).astype("float32"))
            for _ in range(n)]


def _build_step(momentum=0.9, lr=0.05):
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    net.hybridize()
    args = {"learning_rate": lr}
    if momentum:
        args["momentum"] = momentum
    trainer = gluon.Trainer(net.collect_params(), "sgd", args)
    l2 = gluon.loss.L2Loss()
    step = gluon.train_step(net, lambda o, t: l2(o, t), trainer)
    return net, trainer, step


def _train(batches, monkeypatch, health="0", action="record", fault=None,
           momentum=0.9):
    monkeypatch.setenv("MXTPU_HEALTH", health)
    monkeypatch.setenv("MXTPU_HEALTH_ACTION", action)
    faultpoint.reset()
    healthmon.reset()
    if fault:
        faultpoint.configure({"health.grad.corrupt": fault})
    net, trainer, step = _build_step(momentum=momentum)
    losses = []
    for x, y in batches:
        loss = step(mx.nd.array(x), mx.nd.array(y), batch_size=x.shape[0])
        losses.append(float(loss.asnumpy().sum()))
    params = [p.data().asnumpy().copy()
              for _, p in sorted(net.collect_params().items())]
    faultpoint.reset()
    return losses, params, net, trainer, step


def _assert_bitwise(pa, pb):
    assert len(pa) == len(pb)
    for a, b in zip(pa, pb):
        assert np.array_equal(a, b)


# With _COMPILE_THRESHOLD=2, batches 0-1 run eager-warming, batch 2 is
# the compile step; the corruption operand is consulted once per
# fused-path call, so skip=K in the fault spec poisons batch K+2.
_WARMUP = 2


# -- graph_summary units -----------------------------------------------------

class TestGraphSummary:
    def test_per_bucket_indicators_and_norms(self):
        import jax.numpy as jnp
        g0 = jnp.asarray([1.0, float("nan"), 2.0], jnp.float32)
        g1 = jnp.asarray([[3.0, float("inf")]], jnp.float32)
        w0 = jnp.asarray([1.0, 1.0, 1.0], jnp.float32)
        w1 = jnp.asarray([[0.5, -4.0]], jnp.float32)
        loss = jnp.asarray([0.1, 0.2], jnp.float32)
        packed, ok = healthmon.graph_summary(
            [[0], [1]], (g0, g1), (w0, w1), loss)
        s = healthmon.unpack_summary(packed, 2)
        # a NaN/inf anywhere in a bucket poisons its sumsq: the bad
        # flags are derived indicators, no per-element count pass
        assert [int(v) for v in s["g_bad"]] == [1, 1]
        assert [int(v) for v in s["w_bad"]] == [0, 0]
        assert float(s["w_sumsq"][0]) == pytest.approx(3.0)
        assert float(s["w_sumsq"][1]) == pytest.approx(16.25)
        assert int(s["loss_bad"]) == 0
        assert float(s["loss_sum"]) == pytest.approx(0.3, rel=1e-6)
        assert float(s["loss_absmax"]) == pytest.approx(0.2, rel=1e-6)
        assert not bool(ok)
        assert not s["ok"]

    def test_multi_leaf_bucket_folds(self):
        import jax.numpy as jnp
        g0 = jnp.asarray([1.0, 2.0], jnp.float32)
        g1 = jnp.asarray([3.0], jnp.float32)
        w = jnp.ones((1,), jnp.float32)
        packed, ok = healthmon.graph_summary(
            [[0, 1]], (g0, g1), (w, w),
            jnp.asarray([0.5], jnp.float32))
        s = healthmon.unpack_summary(packed, 1)
        assert float(s["g_sumsq"][0]) == pytest.approx(14.0)
        assert int(s["g_bad"][0]) == 0 and bool(ok)

    def test_exploding_but_finite_overflow_flags(self):
        import jax.numpy as jnp
        # elements finite but sumsq overflows f32: an exploding bucket
        # is exactly what the sentinel should flag
        g = jnp.full((4,), 3e19, jnp.float32)
        w = jnp.ones((4,), jnp.float32)
        packed, ok = healthmon.graph_summary(
            [[0]], (g,), (w,), jnp.asarray([0.1], jnp.float32))
        s = healthmon.unpack_summary(packed, 1)
        assert int(s["g_bad"][0]) == 1
        assert not bool(ok)

    def test_clean_summary_is_ok(self):
        import jax.numpy as jnp
        g = jnp.ones((4,), jnp.float32)
        packed, ok = healthmon.graph_summary([[0]], (g,), (g,), g)
        s = healthmon.unpack_summary(packed, 1)
        assert bool(ok) and s["ok"]
        assert int(s["g_bad"][0]) == 0

    def test_nan_loss_flags_not_ok(self):
        import jax.numpy as jnp
        g = jnp.ones((4,), jnp.float32)
        loss = jnp.asarray([1.0, float("nan")], jnp.float32)
        packed, ok = healthmon.graph_summary([[0]], (g,), (g,), loss)
        s = healthmon.unpack_summary(packed, 1)
        assert int(s["loss_bad"]) == 1
        assert not bool(ok)

    def test_apply_corruption_identity_at_zero(self):
        import jax.numpy as jnp
        g = jnp.asarray([0.25, -0.0, 1e-30, -3.5], jnp.float32)
        out = healthmon.apply_corruption((g,), jnp.float32(0.0))[0]
        assert np.array_equal(np.asarray(out), np.asarray(g))
        # sign of zero preserved (x * 1.0, not x + 0.0)
        assert np.signbit(np.asarray(out)[1])

    def test_corruption_operand_maps_exception_types(self):
        faultpoint.configure(
            {"health.grad.corrupt": "raise:OverflowError@n=1"})
        assert healthmon.corruption_operand() == float("inf")
        faultpoint.configure(
            {"health.grad.corrupt": "raise:ArithmeticError@n=1"})
        assert np.isnan(healthmon.corruption_operand())
        faultpoint.configure(
            {"health.grad.corrupt": "raise:ValueError@n=1"})
        assert healthmon.corruption_operand() == 1.0
        # disarmed (n exhausted): clean zero
        assert healthmon.corruption_operand() == 0.0
        faultpoint.reset()
        assert healthmon.corruption_operand() == 0.0


# -- fused-step sentinel integration ----------------------------------------

class TestSentinels:
    def test_fault_free_bitwise_identical_to_health_off(self, monkeypatch):
        """The acceptance twin: sentinels must not perturb what they
        observe — same losses, bitwise-same final params."""
        batches = _batches(8)
        l0, p0, _, _, _ = _train(batches, monkeypatch, health="0")
        l1, p1, _, _, step = _train(batches, monkeypatch, health="1")
        assert step.last_mode == "fused"
        assert l0 == l1
        _assert_bitwise(p0, p1)
        st = healthmon.stats()
        assert st["anomalies"] == 0
        assert st["steps"] == len(batches) - _WARMUP

    def test_sentinels_count_fused_steps_only(self, monkeypatch):
        batches = _batches(5)
        _train(batches, monkeypatch, health="1")
        # warming steps run eagerly: no sentinel, no digest for them
        assert healthmon.stats()["steps"] == len(batches) - _WARMUP
        assert healthmon.last_digest()[0] == len(batches) - _WARMUP

    def test_env_flip_retraces_cleanly(self, monkeypatch):
        """MXTPU_HEALTH is a compile-signature token: flipping it
        mid-run lands on a fresh cache entry (warm + compile again)
        and the sentinels engage — never a stale replay of the other
        graph."""
        batches = _batches(10)
        monkeypatch.setenv("MXTPU_HEALTH", "0")
        net, trainer, step = _build_step()
        for x, y in batches[:4]:
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        assert step.last_mode == "fused"
        assert healthmon.stats()["steps"] == 0
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        modes = []
        for x, y in batches[4:]:
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
            modes.append(step.last_mode)
        # fresh key: one warming step, the compile, then fused hits
        # (the config was already seen once under the old token set is
        # irrelevant — the token is part of the key, so warming restarts)
        assert modes[:2] == ["eager-warming", "compile"]
        assert modes[-1] == "fused"
        # the compile step runs the sentinels too: only the warming
        # step is unchecked
        assert healthmon.stats()["steps"] == len(modes) - 1

    def test_action_flip_retraces(self, monkeypatch):
        batches = _batches(8)
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        monkeypatch.setenv("MXTPU_HEALTH_ACTION", "record")
        net, trainer, step = _build_step()
        for x, y in batches[:4]:
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        assert step.last_mode == "fused"
        monkeypatch.setenv("MXTPU_HEALTH_ACTION", "skip_step")
        x, y = batches[4]
        step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        assert step.last_mode == "eager-warming"  # new key, warms again

    def test_nonfinite_trips_exactly_one_dump_per_episode(
            self, monkeypatch, tmp_path):
        batches = _batches(9)
        _train(batches, monkeypatch, health="1", action="record",
               fault="raise:ArithmeticError@n=1@skip=1")
        st = healthmon.stats()
        # record mode lets the NaN poison the weights: every later step
        # is anomalous too — still ONE episode, ONE dump
        assert st["nonfinite_steps"] >= 1
        assert st["episodes"] == 1
        assert st["dumps"] == 1
        dumps = [p for p in os.listdir(str(tmp_path / "frec"))
                 if "_numerics_" in p]
        assert len(dumps) == 1

    def test_dump_names_bucket_params_and_suspect_rank(
            self, monkeypatch, tmp_path):
        batches = _batches(6)
        _, _, net, _, _ = _train(
            batches, monkeypatch, health="1", action="skip_step",
            fault="raise:ArithmeticError@n=1@skip=1")
        shard = flightrec.last_dumps()[-1]
        data = json.load(open(shard))
        assert data["metadata"]["trigger"] == "numerics"
        info = data["metadata"]["trigger_info"]
        assert info["reason"] == "nonfinite"
        # detected WITHIN the poisoned step: skip=1 passes the compile
        # step (checked seq 1) and fires on checked step 2
        assert info["step"] == 2
        assert healthmon.stats()["last_anomaly_step"] == 2
        assert info["suspect_rank"] == profiler.PID
        assert info["skipped"] is True
        param_names = set(net.collect_params())
        named = {p for b in info["offending_buckets"]
                 for p in b["params"]}
        assert named and named <= param_names
        # the bundled per-layer pass names the poisoned layers exactly
        layer = {r["name"]: r for r in info["layer_stats"]}
        assert set(layer) <= param_names
        assert any(r["g_nonfinite"] > 0 for r in layer.values())
        assert info["loss_window"]  # last-K losses ride along

    def test_episode_rearms_after_clean_step(self, monkeypatch):
        batches = _batches(12)
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        monkeypatch.setenv("MXTPU_HEALTH_ACTION", "skip_step")
        healthmon.reset()
        net, trainer, step = _build_step()
        faultpoint.configure(
            {"health.grad.corrupt": "raise:ArithmeticError@n=1@skip=1"})
        for x, y in batches[:6]:
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        assert healthmon.stats()["dumps"] == 1
        assert not healthmon.stats()["in_episode"]  # clean steps since
        faultpoint.configure(
            {"health.grad.corrupt": "raise:ArithmeticError@n=1"})
        for x, y in batches[6:]:
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        st = healthmon.stats()
        assert st["episodes"] == 2
        assert st["dumps"] == 2
        faultpoint.reset()

    def test_skip_step_bitwise_equals_step_never_happened(
            self, monkeypatch):
        """The acceptance pin: a skipped poisoned update leaves params,
        optimizer state AND update counts exactly as if the poisoned
        step had never run."""
        batches = _batches(8)
        poisoned = _WARMUP + 1  # skip=1 -> the 2nd fused-path call
        _, p_skip, _, tr_skip, _ = _train(
            batches, monkeypatch, health="1", action="skip_step",
            fault="raise:ArithmeticError@n=1@skip=1")
        assert healthmon.stats()["skipped_steps"] == 1
        ref = batches[:poisoned] + batches[poisoned + 1:]
        _, p_ref, _, tr_ref, _ = _train(ref, monkeypatch, health="0")
        _assert_bitwise(p_skip, p_ref)
        assert tr_skip._optimizer.num_update == \
            tr_ref._optimizer.num_update

    def test_skip_step_counts_goodput_event(self, monkeypatch, tmp_path):
        monkeypatch.setenv("MXTPU_RUNS_DIR", str(tmp_path / "runs"))
        goodput.reset()
        goodput.open_run(run_id="health_test")
        try:
            _train(_batches(6), monkeypatch, health="1",
                   action="skip_step",
                   fault="raise:ArithmeticError@n=1@skip=1")
        finally:
            manifest = goodput.close_run()
        kinds = [e.get("kind") for e in manifest.get("events", [])]
        assert "health_skip_step" in kinds

    def test_halt_raises_and_rolls_back(self, monkeypatch):
        batches = _batches(8)
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        monkeypatch.setenv("MXTPU_HEALTH_ACTION", "halt")
        healthmon.reset()
        net, trainer, step = _build_step()
        faultpoint.configure(
            {"health.grad.corrupt": "raise:ArithmeticError@n=1@skip=1"})
        applied = 0
        with pytest.raises(healthmon.HealthHaltError):
            for x, y in batches:
                step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
                applied += 1
        faultpoint.reset()
        st = healthmon.stats()
        assert st["halts"] == 1
        # the halted step's count bookkeeping was rolled back, and the
        # in-graph select kept finite weights behind
        assert trainer._optimizer.num_update == applied
        for _, p in sorted(net.collect_params().items()):
            assert np.isfinite(p.data().asnumpy()).all()
        # adopt-then-raise (review fix): the halted step's outputs WERE
        # adopted before the raise — the poisoned grads landed in the
        # param grad buffers, proving the params hold the program's
        # (clean, selected) output buffers rather than donated inputs
        assert any(not np.isfinite(p.grad().asnumpy()).all()
                   for _, p in sorted(net.collect_params().items()))

    def test_finite_bitflip_is_invisible_locally_but_moves_digest(
            self, monkeypatch):
        """A finite corruption (grads doubled — the pure SDC shape) by
        design does NOT trip the nonfinite sentinel; the grad-bucket
        digest is what catches it, cross-rank."""
        batches = _batches(6)
        _train(batches, monkeypatch, health="1")
        clean_seq, clean_sum = healthmon.last_digest()
        _train(batches, monkeypatch, health="1",
               fault="raise:ValueError@n=1@skip=%d"
               % (len(batches) - _WARMUP - 1))
        bad_seq, bad_sum = healthmon.last_digest()
        assert healthmon.stats()["nonfinite_steps"] == 0
        assert bad_seq == clean_seq
        assert bad_sum != clean_sum

    def test_loss_spike_detected_and_record_only(self, monkeypatch):
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        monkeypatch.setenv("MXTPU_HEALTH_ACTION", "skip_step")
        healthmon.reset()
        healthmon.configure(loss_factor=5.0, min_samples=3)
        net, trainer, step = _build_step(lr=0.0)
        rs = np.random.RandomState(0)
        x = rs.rand(8, 8).astype("float32")
        y = rs.rand(8, 4).astype("float32")
        for _ in range(7):
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        step(mx.nd.array(x), mx.nd.array(y * 1e4), batch_size=8)
        st = healthmon.stats()
        assert st["loss_spikes"] == 1
        assert st["nonfinite_steps"] == 0
        # a finite spike is known only after the donated buffers
        # committed: record-only under every action
        assert st["skipped_steps"] == 0
        assert st["dumps"] == 1

    def test_spiked_loss_stays_out_of_median_window(self, monkeypatch):
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        healthmon.reset()
        healthmon.configure(loss_factor=5.0, min_samples=3)
        net, trainer, step = _build_step(lr=0.0)
        rs = np.random.RandomState(0)
        x = rs.rand(8, 8).astype("float32")
        y = rs.rand(8, 4).astype("float32")
        for _ in range(7):
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        med_before = healthmon.stats()["loss_median"]
        for _ in range(2):
            step(mx.nd.array(x), mx.nd.array(y * 1e4), batch_size=8)
        st = healthmon.stats()
        assert st["loss_spikes"] == 2
        assert st["loss_median"] == med_before

    def test_raising_note_step_never_skips_adoption(self, monkeypatch):
        """Review fix: the sentinel host half runs AFTER the rollback
        try — a raising telemetry path (buggy Monitor stat_func, torn
        fetch) is swallowed and counted, and the committed program's
        outputs still adopt (under donation they are the only valid
        weights left)."""
        from mxnet_tpu.gluon import fused_step as fs
        batches = _batches(6)
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        healthmon.reset()
        net, trainer, step = _build_step()
        for x, y in batches[:4]:
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        assert step.last_mode == "fused"
        before = [p.data().asnumpy().copy()
                  for _, p in sorted(net.collect_params().items())]
        errs = fs.stats()["health_errors"]

        def boom(*a, **k):
            raise RuntimeError("telemetry bug")
        monkeypatch.setattr(healthmon, "note_step", boom)
        x, y = batches[4]
        loss = step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        assert step.last_mode == "fused"
        assert np.isfinite(loss.asnumpy()).all()
        assert fs.stats()["health_errors"] == errs + 1
        after = [p.data().asnumpy()
                 for _, p in sorted(net.collect_params().items())]
        # the update WAS applied (adoption ran despite the raise)
        assert any(not np.array_equal(a, b)
                   for a, b in zip(before, after))

    def test_anomaly_marker_lands_in_health_lane(self, monkeypatch):
        assert profiler.LANES["health"] == 9
        _train(_batches(6), monkeypatch, health="1", action="skip_step",
               fault="raise:ArithmeticError@n=1@skip=1")
        names = [e[1] for e in flightrec.snapshot()
                 if not isinstance(e, str) and e[0] == "i"]
        assert "health:nonfinite" in names
        marks = [e for e in flightrec.snapshot() if not isinstance(e, str)
                 and e[0] == "i" and e[1] == "health:nonfinite"]
        assert marks[0][3] == profiler.LANES["health"]


class TestMeshSentinels:
    def test_mesh_health_sentinels_detect(self, monkeypatch):
        """Mesh mode: the summary rides the shard_map program (loss
        stats psum'd so every replica sees the global values), and the
        corruption operand lands post-reduction — the SDC shape."""
        import jax
        from mxnet_tpu.parallel import create_mesh
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        monkeypatch.setenv("MXTPU_HEALTH_ACTION", "record")
        healthmon.reset()
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
        net.initialize()
        net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05})
        l2 = gluon.loss.L2Loss()
        mesh = create_mesh(devices=jax.devices()[:4])
        step = gluon.train_step(net, lambda o, t: l2(o, t), trainer,
                                mesh=mesh)
        batches = _batches(6)
        for x, y in batches:
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        assert step.last_mode == "fused"
        st = healthmon.stats()
        assert st["steps"] > 0 and st["anomalies"] == 0
        assert healthmon.last_digest() is not None
        faultpoint.configure(
            {"health.grad.corrupt": "raise:ArithmeticError@n=1"})
        x, y = batches[0]
        step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        faultpoint.reset()
        st = healthmon.stats()
        assert st["nonfinite_steps"] == 1
        assert st["dumps"] == 1
        # mesh-DP grads are psum'd in-graph (bitwise-shared): THIS
        # digest is publishable, and a real heartbeat carries it to
        # the server's SDC gauges — the end-to-end wire path
        assert healthmon.shared_digest() == healthmon.last_digest()
        from mxnet_tpu import kvstore_async as KA
        import weakref as _weakref
        monkeypatch.setattr(KA, "_SERVERS", _weakref.WeakSet())
        srv = KA.AsyncPSServer()
        try:
            cli = KA.AsyncPSClient("127.0.0.1", srv.port)
            cli.init("w", np.zeros(2, np.float32))
            cli.heartbeat(0, sync_clock=True)
            ks = KA._server_stats()
            assert ks["rank_health_seq.0"] == \
                healthmon.shared_digest()[0]
        finally:
            srv.stop()


# -- per-layer pass + Monitor routing ----------------------------------------

class TestMonitorRouting:
    def test_hybridized_install_warns_when_health_off(self, monkeypatch,
                                                      caplog):
        monkeypatch.delenv("MXTPU_HEALTH", raising=False)
        net, _, _ = _build_step()
        mon = Monitor(interval=1)
        with caplog.at_level(logging.WARNING):
            mon.install(net)
        assert any("hybridized" in r.message for r in caplog.records)
        with pytest.raises(ValueError, match="hybridized"):
            Monitor(interval=1).install(net, strict=True)

    def test_install_on_eager_block_does_not_warn(self, monkeypatch,
                                                  caplog):
        mx.random.seed(0)
        net = nn.Dense(4)
        net.initialize()
        mon = Monitor(interval=1)
        with caplog.at_level(logging.WARNING):
            mon.install(net)
        assert not caplog.records

    def test_hybridized_hooks_silently_empty_regression(self,
                                                        monkeypatch):
        """The satellite bug, pinned: with the health plane OFF, a
        hybridized block's forward produces ZERO hook (`*_output*`)
        rows — the cached program bypasses Python hooks (and the trace
        step's tracer hits are dropped instead of crashing toc)."""
        monkeypatch.delenv("MXTPU_HEALTH", raising=False)
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(8), nn.Dense(4))
        net.initialize()
        net.hybridize()
        mon = Monitor(interval=1)
        mon.install(net)
        x = mx.nd.array(np.ones((2, 8), np.float32))
        hook_rows = []
        for _ in range(4):  # first call may run eagerly (deferred
            mon.tic()       # init); later ones replay the cache
            net(x).wait_to_read()
            rows = mon.toc()
            hook_rows.append([r for r in rows if "_output" in r[1]])
        # once the program is cached, hook rows are empty forever —
        # the bug install() now warns about (and healthmon replaces)
        assert hook_rows[-1] == [] and hook_rows[-2] == []

    def test_fused_rows_on_monitor_interval(self, monkeypatch):
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        healthmon.reset()
        net, trainer, step = _build_step()
        mon = Monitor(interval=2)
        mon.install(net)
        batches = _batches(8)
        per_batch = []
        for x, y in batches:
            mon.tic()
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
            per_batch.append(mon.toc())
        param_names = sorted(net.collect_params())
        # fused interval batches (2, 4, 6): one weight + one grad row
        # per trainable param, delivered from the health outputs
        for i in (4, 6):
            rows = per_batch[i]
            names = [r[1] for r in rows]
            assert sorted(n for n in names if not n.endswith("_grad")) \
                == param_names
            assert sorted(names) == sorted(
                param_names + [n + "_grad" for n in param_names])
            # no duplicates: healthmon delivery REPLACES the eager
            # collect_params sweep for the hybridized block
            assert len(names) == len(set(names))
        # off-interval batches return nothing
        assert per_batch[3] == [] and per_batch[5] == []
        assert healthmon.stats()["monitor_rows"] > 0

    def test_two_monitors_two_nets_no_crosstalk(self, monkeypatch):
        """Review fix: delivery is scoped to the installed block's
        parameters — monitor B (on an idle second net) receives NO rows
        from net A's fused step, and B's own eager sweep still runs."""
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        healthmon.reset()
        net_a, trainer, step = _build_step()
        mx.random.seed(1)
        net_b = nn.HybridSequential()
        net_b.add(nn.Dense(4, in_units=3))
        net_b.initialize()
        net_b.hybridize()
        mon_a, mon_b = Monitor(interval=1), Monitor(interval=1)
        mon_a.install(net_a)
        mon_b.install(net_b)
        for x, y in _batches(4):
            mon_a.tic()
            mon_b.tic()
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
            rows_a = mon_a.toc()
            rows_b = mon_b.toc()
        assert step.last_mode == "fused"
        names_a = {r[1] for r in rows_a}
        assert names_a and all(
            n.replace("_grad", "") in set(net_a.collect_params())
            for n in names_a)
        # B saw none of A's params, and its own eager sweep survived
        names_b = {r[1].replace("_grad", "") for r in rows_b}
        assert names_b == set(net_b.collect_params())

    def test_pattern_filtered_monitor_keeps_eager_sweep(self,
                                                        monkeypatch):
        """Review fix: a monitor whose pattern matches none of the
        delivered names gets ZERO rows counted and is NOT marked
        fused-delivered — its own eager sweep (which applies the same
        filter) still runs, and monitor_rows stays truthful."""
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        healthmon.reset()
        net, trainer, step = _build_step()
        mon = Monitor(interval=1, pattern=".*output.*")
        mon.install(net)
        for x, y in _batches(4):
            mon.tic()
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
            rows = mon.toc()
        assert step.last_mode == "fused"
        assert rows == []  # nothing matches, nothing fabricated
        assert healthmon.stats()["monitor_rows"] == 0
        assert getattr(mon, "_fused_batch", None) is None

    def test_hybridize_after_install_still_routes(self, monkeypatch,
                                                  caplog):
        """Review fix: install attaches the block regardless of
        hybridization state — hybridize() AFTER install still delivers
        rows with the health plane on, and with it off the bypass is
        warned at the first bypassed toc() instead of staying silent."""
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        healthmon.reset()
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize()
        mon = Monitor(interval=1)
        mon.install(net)       # NOT hybridized yet
        net.hybridize()        # the late hybridize
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05})
        l2 = gluon.loss.L2Loss()
        step = gluon.train_step(net, lambda o, t: l2(o, t), trainer)
        rows = []
        for x, y in _batches(5):
            mon.tic()
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
            rows = mon.toc()
        assert step.last_mode == "fused"
        assert {r[1].replace("_grad", "") for r in rows} \
            == set(net.collect_params())
        # and with the plane OFF: the first bypassed toc warns, once
        monkeypatch.delenv("MXTPU_HEALTH", raising=False)
        mx.random.seed(0)
        net2 = nn.HybridSequential()
        net2.add(nn.Dense(4))
        net2.initialize()
        mon2 = Monitor(interval=1)
        mon2.install(net2)
        net2.hybridize()
        x = mx.nd.array(np.ones((2, 8), np.float32))
        with caplog.at_level(logging.WARNING):
            for _ in range(3):
                mon2.tic()
                net2(x).wait_to_read()
                mon2.toc()
        warns = [r for r in caplog.records if "hybridized" in r.message]
        assert len(warns) == 1

    def test_interval_layer_passes(self, monkeypatch):
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        healthmon.reset()
        healthmon.configure(interval=3)
        net, trainer, step = _build_step()
        batches = _batches(2 + 9)  # 2 warmup + 9 fused-path steps
        for x, y in batches:
            step(mx.nd.array(x), mx.nd.array(y), batch_size=8)
        st = healthmon.stats()
        assert st["steps"] == 9
        assert st["layer_passes"] == 3  # steps 3, 6, 9 only
        rows = healthmon.last_layer_stats()
        assert sorted(n for n, _ in rows) == sorted(net.collect_params())
        for _, r in rows:
            assert r["g_nonfinite"] == 0 and r["w_nonfinite"] == 0
            assert r["w_l2"] > 0


# -- cross-rank SDC divergence ------------------------------------------------

@pytest.fixture
def _only_my_servers(monkeypatch):
    """_server_stats aggregates over every live AsyncPSServer; a
    stopped-but-uncollected server from an earlier test would leak
    phantom ranks/digests into these exact-gauge assertions. Give each
    unit test a private registry."""
    import weakref
    from mxnet_tpu import kvstore_async as KA
    monkeypatch.setattr(KA, "_SERVERS", weakref.WeakSet())


class TestSDCDivergence:
    def _beat(self, srv, rank, digest):
        from mxnet_tpu import kvstore_async as KA
        cli = KA.AsyncPSClient("127.0.0.1", srv.port)
        cli.init("w%d" % rank, np.zeros(2, np.float32))  # negotiate v1
        # simulate this rank's mesh-DP digest (digest_shared: only
        # bitwise-shared-grads programs publish — review fix)
        healthmon._state["digest"] = digest
        healthmon._state["digest_shared"] = True
        cli.heartbeat(rank, sync_clock=True)
        return cli

    def test_local_digest_never_rides_the_heartbeat(self,
                                                    _only_my_servers,
                                                    monkeypatch):
        """Review fix: a single-device (non-replicated) digest would
        false-diverge on every healthy step — it stays local. The
        fused step marks replication per program, and only a
        replicated digest reaches the wire."""
        from mxnet_tpu import kvstore_async as KA
        _train(_batches(5), monkeypatch, health="1")
        assert healthmon.last_digest() is not None   # local gauge
        assert healthmon.shared_digest() is None     # not publishable
        watchdog._last = (7, 0.01)
        srv = KA.AsyncPSServer()
        try:
            cli = KA.AsyncPSClient("127.0.0.1", srv.port)
            cli.init("w", np.zeros(2, np.float32))
            cli.heartbeat(0, sync_clock=True)
            ks = KA._server_stats()
            assert "rank_health_seq.0" not in ks
        finally:
            srv.stop()

    def test_digest_rides_heartbeat_and_agreement_is_clean(self, _only_my_servers):
        from mxnet_tpu import kvstore_async as KA
        watchdog._last = (7, 0.01)
        srv = KA.AsyncPSServer()
        try:
            self._beat(srv, 0, (7, 12345))
            self._beat(srv, 1, (7, 12345))
            ks = KA._server_stats()
            assert ks["rank_health_seq.0"] == 7
            assert ks["rank_health_seq.1"] == 7
            assert ks["sdc_divergence"] == 0
            assert ks["sdc_suspects"] == []
        finally:
            srv.stop()

    def test_two_rank_divergence_flags_both(self, _only_my_servers):
        from mxnet_tpu import kvstore_async as KA
        watchdog._last = (7, 0.01)
        srv = KA.AsyncPSServer()
        try:
            self._beat(srv, 0, (7, 1111))
            self._beat(srv, 1, (7, 2222))
            ks = KA._server_stats()
            assert ks["sdc_divergence"] == 1
            assert ks["sdc_checked_seq"] == 7
            # two ranks: divergence certain, attribution not — both
            assert ks["sdc_suspects"] == [0, 1]
            assert ks["sdc_suspect.0"] == 1 and ks["sdc_suspect.1"] == 1
        finally:
            srv.stop()

    def test_three_rank_majority_names_the_suspect(self, _only_my_servers):
        from mxnet_tpu import kvstore_async as KA
        watchdog._last = (7, 0.01)
        srv = KA.AsyncPSServer()
        try:
            self._beat(srv, 0, (7, 1111))
            self._beat(srv, 1, (7, 2222))
            self._beat(srv, 2, (7, 1111))
            ks = KA._server_stats()
            assert ks["sdc_divergence"] == 1
            assert ks["sdc_suspects"] == [1]
            assert "sdc_suspect.0" not in ks
        finally:
            srv.stop()

    def test_mismatched_seqs_not_compared(self, _only_my_servers):
        from mxnet_tpu import kvstore_async as KA
        watchdog._last = (7, 0.01)
        srv = KA.AsyncPSServer()
        try:
            self._beat(srv, 0, (7, 1111))
            self._beat(srv, 1, (8, 2222))  # different step: no verdict
            ks = KA._server_stats()
            assert "sdc_divergence" not in ks
            assert "sdc_suspects" not in ks
        finally:
            srv.stop()

    def test_digest_rides_without_watchdog(self, _only_my_servers):
        """Review fix: MXTPU_WATCHDOG=0 leaves last_step() None forever
        — the digest must still ride (placeholder step pair, seq=-1),
        and the placeholder must NOT enter the straggler gauges."""
        from mxnet_tpu import kvstore_async as KA
        watchdog._last = None
        srv = KA.AsyncPSServer()
        try:
            cli = KA.AsyncPSClient("127.0.0.1", srv.port)
            cli.init("w", np.zeros(2, np.float32))
            healthmon._state["digest"] = (9, 4242)
            healthmon._state["digest_shared"] = True
            cli.heartbeat(0, sync_clock=True)
            ks = KA._server_stats()
            assert ks["rank_health_seq.0"] == 9
            assert "rank_step_s.0" not in ks
            assert "rank_step_seq.0" not in ks
        finally:
            srv.stop()

    def test_corrupted_rank_diverges_on_the_wire(self, monkeypatch,
                                                 _only_my_servers):
        """End-to-end 2-rank acceptance: train rank 0 clean and rank 1
        with a finite bit-flip corruption on the same data, publish
        both digests over real heartbeats, and the server flags the
        divergence naming rank 1 among the suspects."""
        from mxnet_tpu import kvstore_async as KA
        batches = _batches(6)
        _train(batches, monkeypatch, health="1")
        clean = healthmon.last_digest()
        _train(batches, monkeypatch, health="1",
               fault="raise:ValueError@n=1@skip=%d"
               % (len(batches) - _WARMUP - 1))
        bad = healthmon.last_digest()
        assert clean[0] == bad[0] and clean[1] != bad[1]
        watchdog._last = (clean[0], 0.01)
        srv = KA.AsyncPSServer()
        try:
            self._beat(srv, 0, clean)
            self._beat(srv, 1, bad)
            ks = KA._server_stats()
            assert ks["sdc_divergence"] == 1
            assert 1 in ks["sdc_suspects"]
        finally:
            srv.stop()


# -- TensorInspector device paths --------------------------------------------

class TestTensorInspector:
    def test_snapshot_single_transfer(self, monkeypatch):
        import jax
        calls = []
        real = jax.device_get

        def spy(x):
            calls.append(1)
            return real(x)
        monkeypatch.setattr(jax, "device_get", spy)
        tensors = [mx.nd.array(np.full((3,), i, np.float32))
                   for i in range(5)]
        tensors[2][1] = float("nan")
        insp = TensorInspector.snapshot(tensors)
        assert len(calls) == 1  # ONE batched transfer, not per tensor
        assert [i.has_nan_or_inf() for i in insp] \
            == [False, False, True, False, False]
        assert insp[2].check_value() == [(1,)]

    def test_snapshot_dict_tags(self):
        out = TensorInspector.snapshot(
            {"a": np.zeros(2), "b": np.ones(2)})
        assert set(out) == {"a", "b"}
        assert out["a"].tag == "a"
        assert "a 2" in out["a"].print_string()

    def test_ndarray_constructor_still_works(self):
        t = TensorInspector(mx.nd.array(np.eye(2)), tag="eye")
        assert not t.has_nan_or_inf()
        assert "eye 2x2" in t.print_string()

    def test_print_in_trace_inside_jit(self, capsys):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return TensorInspector.print_in_trace(x, tag="probe") * 2.0

        x = jnp.asarray([1.0, float("nan"), 3.0], jnp.float32)
        y = f(x)
        jax.effects_barrier()
        out = capsys.readouterr().out
        assert "TensorInspector[probe]" in out
        assert "nonfinite=1" in out
        # the probe is an identity: the traced value is unchanged
        assert np.array_equal(np.asarray(y)[::2],
                              np.asarray(x)[::2] * 2.0)

    def test_braced_tag_is_format_safe(self, capsys):
        """Review fix: a '{'/'}'-bearing tag must not corrupt the
        jax.debug.print format string and abort the user's trace."""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return TensorInspector.print_in_trace(x, tag="block{0}.d")

        f(jnp.ones((2,), jnp.float32))
        jax.effects_barrier()
        assert "block{0}.d" in capsys.readouterr().out

    def test_check_in_trace_counts_nonfinite(self, capsys):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return TensorInspector.check_in_trace(x, tag="g")

        f(jnp.asarray([1.0, float("inf")], jnp.float32))
        jax.effects_barrier()
        assert "nonfinite=1" in capsys.readouterr().out


# -- AMP loss-scaler accounting ----------------------------------------------

class TestAmpAccounting:
    def test_overflow_skips_count_with_profiling_off(self):
        from mxnet_tpu.contrib.amp.loss_scaler import LossScaler
        assert not profiler.is_running()
        scaler = LossScaler(init_scale=1024.0, scale_factor=2.0,
                            scale_window=2)
        scaler.update_scale(True)
        h = profiler.metrics()["health"]
        assert h["amp_overflow_skips"] == 1
        assert h["amp_loss_scale"] == 512.0
        scaler.update_scale(False)
        scaler.update_scale(False)  # window hit: scale doubles back
        h = profiler.metrics()["health"]
        assert h["amp_overflow_skips"] == 1
        assert h["amp_scale_updates"] == 3
        assert h["amp_loss_scale"] == 1024.0


# -- surfaces -----------------------------------------------------------------

class TestSurfaces:
    def test_metrics_section_and_dumps_line(self, monkeypatch):
        m = profiler.metrics()
        assert "health" in m
        for key in ("steps", "anomalies", "skipped_steps",
                    "amp_overflow_skips", "enabled", "action"):
            assert key in m["health"]
        assert "health:" in profiler.dumps()

    def test_prometheus_families(self, monkeypatch):
        monkeypatch.setenv("MXTPU_HEALTH", "1")
        healthmon.reset()
        text = profiler.prometheus_text()
        assert 'mxtpu_health_steps_total{rank="%d",kind="checked"}' \
            % profiler.PID in text
        assert "mxtpu_health_anomaly{" in text
        assert "mxtpu_health_loss{" in text
        monkeypatch.setenv("MXTPU_HEALTH", "0")
        assert "mxtpu_health_steps_total" not in \
            profiler.prometheus_text()

    def test_faultpoint_cataloged(self):
        assert "health.grad.corrupt" in faultpoint.POINTS
        # configure() validates against the catalog — a typo'd health
        # point fails loudly
        with pytest.raises(ValueError, match="unknown fault point"):
            faultpoint.configure({"health.grad.corrupted": "raise"})

    def test_numerics_dump_bundles_health_metrics(self, monkeypatch):
        _train(_batches(6), monkeypatch, health="1", action="skip_step",
               fault="raise:ArithmeticError@n=1@skip=1")
        data = json.load(open(flightrec.last_dumps()[-1]))
        h = data["metadata"]["metrics"]["health"]
        assert h["nonfinite_steps"] == 1
        assert h["skipped_steps"] == 1
