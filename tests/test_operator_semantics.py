"""Operator edge-case semantics vs numpy references.

Ports focused cases from tests/python/unittest/test_operator.py where the
reference pins subtle behavior: pooling pad counting, pad modes, LRN,
sequence ops with lengths, topk variants, take modes, one_hot,
depth/space transforms, norm orders, L2Normalization modes, UpSampling."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _a(x):
    return nd.array(np.asarray(x, "float32"))


def test_pooling_avg_count_include_pad():
    x = _a(np.arange(16).reshape(1, 1, 4, 4))
    # include pad: denominator is full window
    inc = nd.Pooling(x, kernel=(3, 3), pool_type="avg", stride=(1, 1),
                     pad=(1, 1), count_include_pad=True)
    exc = nd.Pooling(x, kernel=(3, 3), pool_type="avg", stride=(1, 1),
                     pad=(1, 1), count_include_pad=False)
    # corner (0,0): window values {0,1,4,5}; include: /9, exclude: /4
    assert inc.asnumpy()[0, 0, 0, 0] == pytest.approx((0 + 1 + 4 + 5) / 9.0)
    assert exc.asnumpy()[0, 0, 0, 0] == pytest.approx((0 + 1 + 4 + 5) / 4.0)


def test_pooling_global():
    x = _a(np.arange(16).reshape(1, 1, 4, 4))
    g = nd.Pooling(x, global_pool=True, pool_type="max", kernel=(2, 2))
    assert g.asnumpy().reshape(-1)[0] == 15.0


def test_pad_modes():
    x = _a(np.arange(4).reshape(1, 1, 2, 2))
    c = nd.Pad(x, mode="constant", pad_width=(0, 0, 0, 0, 1, 1, 1, 1),
               constant_value=9.0)
    assert c.shape == (1, 1, 4, 4)
    assert c.asnumpy()[0, 0, 0, 0] == 9.0
    e = nd.Pad(x, mode="edge", pad_width=(0, 0, 0, 0, 1, 1, 1, 1))
    assert e.asnumpy()[0, 0, 0, 0] == 0.0       # replicates corner
    r = nd.Pad(x, mode="reflect", pad_width=(0, 0, 0, 0, 1, 1, 1, 1))
    np.testing.assert_allclose(r.asnumpy()[0, 0, 0], [3, 2, 3, 2])


def test_lrn_formula():
    # LRN: x / (knorm + alpha/n * sum(x^2 over window))^beta
    rs = np.random.RandomState(0)
    x = rs.rand(2, 5, 3, 3).astype("float32")
    out = nd.LRN(_a(x), nsize=3, alpha=1e-4, beta=0.75, knorm=2.0).asnumpy()
    n = 3
    sq = np.zeros_like(x)
    for c in range(5):
        lo, hi = max(0, c - n // 2), min(5, c + n // 2 + 1)
        sq[:, c] = (x[:, lo:hi] ** 2).sum(axis=1)
    ref = x / (2.0 + (1e-4 / n) * sq) ** 0.75
    np.testing.assert_allclose(out, ref, rtol=1e-4)


def test_sequence_ops_with_lengths():
    # data layout: [T, B, ...]
    x = np.arange(12, dtype="float32").reshape(3, 2, 2)
    lens = np.array([2, 3], "float32")
    m = nd.SequenceMask(_a(x), _a(lens), use_sequence_length=True,
                        value=-1.0).asnumpy()
    np.testing.assert_allclose(m[2, 0], [-1, -1])   # beyond len 2
    np.testing.assert_allclose(m[2, 1], x[2, 1])    # within len 3
    last = nd.SequenceLast(_a(x), _a(lens),
                           use_sequence_length=True).asnumpy()
    np.testing.assert_allclose(last[0], x[1, 0])    # t = len-1
    np.testing.assert_allclose(last[1], x[2, 1])
    rev = nd.SequenceReverse(_a(x), _a(lens),
                             use_sequence_length=True).asnumpy()
    np.testing.assert_allclose(rev[0, 0], x[1, 0])  # first two reversed
    np.testing.assert_allclose(rev[2, 0], x[2, 0])  # tail untouched


def test_topk_variants():
    x = _a([[3.0, 1.0, 2.0]])
    v = nd.topk(x, k=2, ret_typ="value").asnumpy()
    np.testing.assert_allclose(v, [[3, 2]])
    i = nd.topk(x, k=2, ret_typ="indices").asnumpy()
    np.testing.assert_allclose(i, [[0, 2]])
    b = nd.topk(x, k=2, ret_typ="mask").asnumpy()
    np.testing.assert_allclose(b, [[1, 0, 1]])
    both = nd.topk(x, k=1, ret_typ="both")
    np.testing.assert_allclose(both[0].asnumpy(), [[3]])
    np.testing.assert_allclose(both[1].asnumpy(), [[0]])
    # smallest
    s = nd.topk(x, k=1, is_ascend=True).asnumpy()
    np.testing.assert_allclose(s, [[1]])


def test_take_modes():
    x = _a([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    idx = _a([0, 4])
    clip = nd.take(x, idx, mode="clip").asnumpy()
    np.testing.assert_allclose(clip[1], [5, 6])     # 4 -> clipped to 2
    wrap = nd.take(x, idx, mode="wrap").asnumpy()
    np.testing.assert_allclose(wrap[1], [3, 4])     # 4 mod 3 = 1


def test_one_hot_options():
    x = _a([0, 2])
    out = nd.one_hot(x, depth=3, on_value=5.0, off_value=-1.0).asnumpy()
    np.testing.assert_allclose(out, [[5, -1, -1], [-1, -1, 5]])


def test_pick_keepdims():
    x = _a([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])
    idx = _a([1, 0])
    out = nd.pick(x, idx, axis=1).asnumpy()
    np.testing.assert_allclose(out, [2, 4])
    out2 = nd.pick(x, idx, axis=1, keepdims=True).asnumpy()
    assert out2.shape == (2, 1)


def test_space_depth_roundtrip():
    x = _a(np.arange(16).reshape(1, 1, 4, 4))
    d = nd.space_to_depth(x, block_size=2)
    assert d.shape == (1, 4, 2, 2)
    back = nd.depth_to_space(d, block_size=2)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy())


def test_norm_orders_and_axes():
    x = _a([[3.0, 4.0], [6.0, 8.0]])
    np.testing.assert_allclose(float(nd.norm(x).asnumpy()),
                               np.sqrt(9 + 16 + 36 + 64), rtol=1e-5)
    l1 = nd.norm(x, ord=1, axis=1).asnumpy()
    np.testing.assert_allclose(l1, [7, 14])
    l2k = nd.norm(x, ord=2, axis=1, keepdims=True).asnumpy()
    np.testing.assert_allclose(l2k, [[5], [10]])


def test_l2_normalization_modes():
    rs = np.random.RandomState(0)
    x = rs.rand(2, 3, 4).astype("float32")
    inst = nd.L2Normalization(_a(x), mode="instance").asnumpy()
    ref = x / np.sqrt((x ** 2).sum(axis=(1, 2), keepdims=True) + 1e-10)
    np.testing.assert_allclose(inst, ref, rtol=1e-4)
    chan = nd.L2Normalization(_a(x), mode="channel").asnumpy()
    refc = x / np.sqrt((x ** 2).sum(axis=1, keepdims=True) + 1e-10)
    np.testing.assert_allclose(chan, refc, rtol=1e-4)


def test_upsampling_nearest():
    x = _a(np.arange(4).reshape(1, 1, 2, 2))
    up = nd.UpSampling(x, scale=2, sample_type="nearest").asnumpy()
    assert up.shape == (1, 1, 4, 4)
    # each source pixel becomes a 2x2 block
    np.testing.assert_allclose(up[0, 0],
                               [[0, 0, 1, 1], [0, 0, 1, 1],
                                [2, 2, 3, 3], [2, 2, 3, 3]])


def test_slice_like_axes():
    b = _a(np.zeros((2, 3)))
    out = nd.slice_like(_a(np.arange(12).reshape(3, 4)), b).asnumpy()
    assert out.shape == (2, 3)
    out2 = nd.slice_like(_a(np.arange(12).reshape(3, 4)), b,
                         axes=(1,)).asnumpy()
    assert out2.shape == (3, 3)


def test_repeat_and_tile():
    x = _a([[1.0, 2.0], [3.0, 4.0]])
    r = nd.repeat(x, repeats=2, axis=1).asnumpy()
    np.testing.assert_allclose(r, [[1, 1, 2, 2], [3, 3, 4, 4]])
    rf = nd.repeat(x, repeats=2).asnumpy()       # flattened when no axis
    np.testing.assert_allclose(rf, [1, 1, 2, 2, 3, 3, 4, 4])
    t = nd.tile(x, reps=(2, 1)).asnumpy()
    assert t.shape == (4, 2)


def test_argsort_and_sort_descending():
    x = _a([3.0, 1.0, 2.0])
    np.testing.assert_allclose(nd.argsort(x).asnumpy(), [1, 2, 0])
    np.testing.assert_allclose(nd.argsort(x, is_ascend=False).asnumpy(),
                               [0, 2, 1])
    np.testing.assert_allclose(nd.sort(x, is_ascend=False).asnumpy(),
                               [3, 2, 1])


def test_grid_generator_bilinear_sampler_identity():
    rs = np.random.RandomState(0)
    img = rs.rand(1, 1, 5, 5).astype("float32")
    affine = _a([[1.0, 0, 0, 0, 1.0, 0]])
    grid = nd.GridGenerator(affine, transform_type="affine",
                            target_shape=(5, 5))
    out = nd.BilinearSampler(_a(img), grid).asnumpy()
    np.testing.assert_allclose(out, img, atol=1e-5)


def test_dot_transpose_flags():
    a = np.arange(6, dtype="float32").reshape(2, 3)
    b = np.arange(12, dtype="float32").reshape(4, 3)
    out = nd.dot(_a(a), _a(b), transpose_b=True).asnumpy()
    np.testing.assert_allclose(out, a @ b.T)
    out2 = nd.dot(_a(a), _a(a), transpose_a=True).asnumpy()
    np.testing.assert_allclose(out2, a.T @ a)


def test_batch_dot():
    rs = np.random.RandomState(0)
    a = rs.rand(2, 3, 4).astype("float32")
    b = rs.rand(2, 4, 5).astype("float32")
    out = nd.batch_dot(_a(a), _a(b)).asnumpy()
    np.testing.assert_allclose(out, a @ b, rtol=1e-5)


def test_where_and_clip():
    cond = _a([1.0, 0.0, 1.0])
    x, y = _a([1.0, 2.0, 3.0]), _a([10.0, 20.0, 30.0])
    np.testing.assert_allclose(nd.where(cond, x, y).asnumpy(), [1, 20, 3])
    np.testing.assert_allclose(
        nd.clip(_a([-2.0, 0.5, 2.0]), 0.0, 1.0).asnumpy(), [0, 0.5, 1])


def test_deconvolution_output_shape():
    x = nd.zeros((1, 2, 4, 4))
    w = nd.zeros((2, 3, 3, 3))
    out = nd.Deconvolution(x, w, kernel=(3, 3), num_filter=3,
                           stride=(2, 2), pad=(1, 1), adj=(1, 1),
                           no_bias=True)
    # out = (in-1)*stride - 2*pad + kernel + adj = 3*2 - 2 + 3 + 1 = 8
    assert out.shape == (1, 3, 8, 8)


def test_instance_norm_numerics():
    rs = np.random.RandomState(0)
    x = rs.rand(2, 3, 4).astype("float32")
    g, b = np.ones(3, "float32") * 2, np.ones(3, "float32")
    out = nd.InstanceNorm(_a(x), _a(g), _a(b), eps=1e-5).asnumpy()
    mean = x.mean(axis=2, keepdims=True)
    var = x.var(axis=2, keepdims=True)
    ref = 2 * (x - mean) / np.sqrt(var + 1e-5) + 1
    np.testing.assert_allclose(out, ref, rtol=1e-4)


def test_embedding_gradient_accumulates():
    from mxnet_tpu import autograd
    w = nd.array(np.zeros((4, 2), "float32"))
    w.attach_grad()
    idx = _a([1, 1, 3])
    with autograd.record():
        out = nd.Embedding(idx, w, input_dim=4, output_dim=2).sum()
    out.backward()
    g = w.grad.asnumpy()
    np.testing.assert_allclose(g[1], [2, 2])   # row 1 hit twice
    np.testing.assert_allclose(g[3], [1, 1])
    np.testing.assert_allclose(g[0], 0)


@pytest.mark.parametrize("K,md,s1,s2,pad", [
    (1, 2, 1, 2, 2),       # FlowNet-style 1x1 kernel
    (3, 2, 1, 1, 2),       # K>1: exercises the kernel-window loop
    (3, 1, 2, 1, 2),       # stride1 > 1
])
def test_correlation_matches_reference_loop(K, md, s1, s2, pad):
    """Correlation vs a direct transcription of the reference's loop
    (ref: src/operator/correlation.cc CorrelationForward — kernel anchored
    top-left: tmp[y1+h][x1+w])."""
    rs = np.random.RandomState(0)
    B, C, H, W = 1, 2, 8, 8
    d1 = rs.rand(B, C, H, W).astype("float32")
    d2 = rs.rand(B, C, H, W).astype("float32")
    out = nd.Correlation(_a(d1), _a(d2), kernel_size=K,
                         max_displacement=md, stride1=s1, stride2=s2,
                         pad_size=pad, is_multiply=True).asnumpy()

    kr = K // 2
    border = md + kr
    pH, pW = H + 2 * pad, W + 2 * pad
    top_h = int(np.ceil((pH - 2 * border) / s1))
    top_w = int(np.ceil((pW - 2 * border) / s1))
    ngr = md // s2
    ngw = 2 * ngr + 1
    t1 = np.zeros((B, pH, pW, C), "float32")
    t2 = np.zeros((B, pH, pW, C), "float32")
    t1[:, pad:pad + H, pad:pad + W] = d1.transpose(0, 2, 3, 1)
    t2[:, pad:pad + H, pad:pad + W] = d2.transpose(0, 2, 3, 1)
    ref = np.zeros((B, ngw * ngw, top_h, top_w), "float32")
    sumelems = K * K * C
    for i in range(top_h):
        for j in range(top_w):
            x1, y1 = j * s1 + md, i * s1 + md
            for tc in range(ngw * ngw):
                s2o = (tc % ngw - ngr) * s2
                s2p = (tc // ngw - ngr) * s2
                for h in range(K):
                    for w in range(K):
                        ref[:, tc, i, j] += (
                            t1[:, y1 + h, x1 + w]
                            * t2[:, y1 + s2p + h, x1 + s2o + w]).sum(-1)
    ref /= sumelems
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, rtol=1e-4)
